"""Storage server role: versioned MVCC reads over pulled log data.

Ref: storageserver.actor.cpp — VersionedData :236-260 (MVCC window),
getValueQ :684 / getKeyValues :1182 read path with waitForVersion :631;
update() pulls mutations from the log via peek and applies them in version
order; atomics are applied at the storage server exactly as the client
would (shared fdbclient/Atomic.h semantics -> client/atomic.py).

Sharding: `owned` maps the key ranges this server serves (ref: serverKeys).
Ownership changes ride the mutation stream itself — every storage intercepts
`\xff/keyServers/` mutations (the ApplyMetadataMutation analog,
fdbserver/ApplyMetadataMutation.h) so a shard handoff happens at an exact
commit version on every role that watches the stream.  A range being
fetched buffers its mutations until the snapshot arrives (ref: AddingShard,
storageserver.actor.cpp:85-133), then replays the tail and goes live when
the settling keyServers record lands.  Reads outside owned ranges fail with
wrong_shard_server (the client invalidates its location cache and retries);
reads below a fetched shard's snapshot version fail transaction_too_old
(ref: the shard's transferredVersion floor in fetchKeys).
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from ..client.atomic import apply_atomic
from ..client.types import Mutation, MutationType, key_after
from ..fileio.kvstore import open_engine
from ..flow.asyncvar import NotifiedVersion
from ..flow.error import FdbError
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.wire import decode_frame, encode_frame
from ..rpc.stream import RequestStream
from ..utils import RangeMap
from .interfaces import (
    TAG_ALL,
    TAG_DEFAULT,
    FetchShardReply,
    FetchShardRequest,
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetShardStateRequest,
    GetValueReply,
    GetValueRequest,
    StorageInterface,
    TLogInterface,
    TLogPeekRequest,
    TLogPopRequest,
    WatchValueRequest,
)

# User + system data lives in [b"", KEYSPACE_END); keys at or beyond it are
# per-engine metadata outside the replicated keyspace (ref: allKeys end
# \xff\xff, fdbclient/SystemData.cpp).
KEYSPACE_END = b"\xff\xff"


class VersionedClears:
    """Versioned clear-range index: key-partitioned stamp lists.

    The key space is a partition (`bounds[i]` starts segment i); each
    segment carries the ascending (version, seq) stamps of every clear
    covering it.  A point query is two binary searches — segment by key,
    stamp by version — replacing the O(#clears) scan the flat list needed
    (the reference's PTree VersionedMap is versioned-ordered for the same
    reason, fdbclient/VersionedMap.h:43).  Inserting a clear splits at its
    endpoints and appends one stamp per covered segment; trim() drops
    expired stamps and coalesces equal neighbours, so the structure stays
    proportional to the LIVE window, not the clear history.
    """

    def __init__(self):
        self.bounds: List[bytes] = [b""]
        self.stamps: List[List[Tuple[int, int]]] = [[]]

    def _split_at(self, key: bytes) -> int:
        """Segment index beginning exactly at `key`, splitting if needed."""
        i = bisect_right(self.bounds, key) - 1
        if self.bounds[i] == key:
            return i
        self.bounds.insert(i + 1, key)
        self.stamps.insert(i + 1, list(self.stamps[i]))
        return i + 1

    def add(self, begin: bytes, end: bytes, version: int, seq: int):
        if begin >= end:
            return
        i = self._split_at(begin)
        j = self._split_at(end)
        for k in range(i, j):
            self.stamps[k].append((version, seq))

    def latest_over(self, key: bytes, version: int) -> Tuple[int, int]:
        i = bisect_right(self.bounds, key) - 1
        st = self.stamps[i]
        p = bisect_right(st, (version, 1 << 62)) - 1
        return st[p] if p >= 0 else (-1, -1)

    def trim(self, through_version: int):
        nb: List[bytes] = [b""]
        ns: List[List[Tuple[int, int]]] = [
            [t for t in self.stamps[0] if t[0] > through_version]
        ]
        for b, st in zip(self.bounds[1:], self.stamps[1:]):
            st2 = [t for t in st if t[0] > through_version]
            if st2 == ns[-1]:
                continue  # identical neighbour: coalesce
            nb.append(b)
            ns.append(st2)
        self.bounds, self.stamps = nb, ns

    def __iter__(self):
        """(version, seq, begin, end) fragments, coverage-equivalent to the
        inserted clears (endpoints may be split finer)."""
        for i, st in enumerate(self.stamps):
            if not st:
                continue
            b = self.bounds[i]
            e = self.bounds[i + 1] if i + 1 < len(self.bounds) else KEYSPACE_END
            for (v, s) in st:
                yield (v, s, b, e)

    def __len__(self):
        return sum(len(st) for st in self.stamps)


class VersionedStore:
    """Per-key version chains + versioned clear-range index (the python
    stand-in for the reference's PTree VersionedMap,
    fdbclient/VersionedMap.h:43).

    Entries are ordered by (version, seq) where seq is the mutation's index
    within its version, so set-then-clear vs clear-then-set of the same key
    inside one commit resolve exactly as the mutation order says.
    """

    _SEQ_INF = 1 << 62

    def __init__(self):
        # key -> [(version, seq, value-or-None)]
        self.kv: Dict[bytes, List[Tuple[int, int, Optional[bytes]]]] = {}
        self.sorted_keys: List[bytes] = []
        self.clears = VersionedClears()

    # -- reads --
    def _latest_clear_over(self, key: bytes, version: int) -> Tuple[int, int]:
        return self.clears.latest_over(key, version)

    def get_stamped(self, key: bytes, version: int):
        """(touched, value): touched=False means no window entry covers the
        key at this version (the caller may fall through to a base engine)."""
        chain = self.kv.get(key)
        stamp_e, val = (-1, -1), None
        if chain:
            i = bisect_right(chain, (version, self._SEQ_INF)) - 1
            if i >= 0:
                ver, seq, val = chain[i]
                stamp_e = (ver, seq)
        stamp_c = self._latest_clear_over(key, version)
        if stamp_c > stamp_e:
            return True, None
        if stamp_e == (-1, -1):
            return False, None
        return True, val

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        _touched, val = self.get_stamped(key, version)
        return val

    def trim(self, through_version: int):
        """Drop window state at versions <= through_version (the base engine
        is durable through it; ref: the MVCC window following durability,
        storageserver updateStorage -> setOldestVersion)."""
        for key in list(self.kv):
            chain = [e for e in self.kv[key] if e[0] > through_version]
            if chain:
                self.kv[key] = chain
            else:
                del self.kv[key]
                i = bisect_left(self.sorted_keys, key)
                if i < len(self.sorted_keys) and self.sorted_keys[i] == key:
                    del self.sorted_keys[i]
        self.clears.trim(through_version)

    def get_range(
        self,
        begin: bytes,
        end: bytes,
        version: int,
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        i = bisect_left(self.sorted_keys, begin)
        j = bisect_left(self.sorted_keys, end)
        keys = self.sorted_keys[i:j]
        if reverse:
            keys = reversed(keys)
        out = []
        for k in keys:
            v = self.get(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    break
        return out

    # -- writes (applied in (version, seq) order by the update loop) --
    def set(self, key: bytes, value: bytes, version: int, seq: int = 0):
        chain = self.kv.get(key)
        if chain is None:
            self.kv[key] = [(version, seq, value)]
            insort(self.sorted_keys, key)
        else:
            chain.append((version, seq, value))

    def clear_range(self, begin: bytes, end: bytes, version: int, seq: int = 0):
        self.clears.add(begin, end, version, seq)


class ByteSample:
    """Sampled per-key byte weights with range sums and weighted split
    points (ref: the byte sample fed by every mutation, StorageMetrics
    .actor.h:404) — backed by the order-statistic IndexedSet
    (utils/indexed_set.py, the flow/IndexedSet.h analog): update, erase,
    range-erase, and range-sum are all O(log n).

    A key of total size s is sampled with probability min(1, s/UNIT) and
    carries weight max(s, UNIT), so the expected weight equals the true
    bytes and small keys stay out of the sample."""

    UNIT = 100

    def __init__(self, rng):
        from ..utils.indexed_set import IndexedSet

        self.rng = rng
        self.idx = IndexedSet(rng)

    def update(self, key: bytes, size: int):
        # Every write RE-SAMPLES the key (ref: byteSample updates on each
        # mutation): keeping a prior admission would bias repeatedly-
        # overwritten small keys into the sample permanently.
        admit = size >= self.UNIT or self.rng.random01() < size / self.UNIT
        if admit:
            self.idx.set(key, max(size, self.UNIT))
        else:
            self.idx.erase(key)

    def remove_range(self, begin: bytes, end: Optional[bytes]):
        self.idx.erase_range(begin, end)

    def bytes_in(self, begin: bytes, end: Optional[bytes]) -> int:
        return self.idx.sum_range(begin, end)

    def split_point(self, begin: bytes, end: Optional[bytes]) -> Optional[bytes]:
        """The sampled key closest to half the range's weight (ref:
        splitMetrics picking the key where half the bytes fall).  Scans
        only the RANGE's sampled keys; key_at_metric offers the O(log n)
        form when closest-to-half precision is not required."""
        ks = self.idx.keys_in(begin, end)
        total = self.idx.sum_range(begin, end)
        if total == 0 or len(ks) < 2:
            return None
        acc = 0
        best, best_err = None, None
        for i, k in enumerate(ks):
            if i > 0:
                err = abs(acc - total / 2)
                if best_err is None or err < best_err:
                    best, best_err = k, err
            acc += self.idx.get(k)
        return best


VERSION_META_KEY = b"\xff\xffmeta/durable_version"
OWNED_META_KEY = b"\xff\xffmeta/owned_ranges"


class AddingShard:
    """A range this server is becoming responsible for (ref: AddingShard
    storageserver.actor.cpp:85-133).  While FETCHING, the stream's mutations
    for the range are buffered (applying them before the base snapshot lands
    would double-apply atomics and break chain ordering); once the snapshot
    at `fetch_version` is in, the buffered tail above it replays and the
    shard waits READY for the settling keyServers record."""

    FETCHING = 0
    READY = 1

    __slots__ = ("begin", "end", "src_ids", "phase", "buffer", "fetch_version",
                 "finalized")

    def __init__(self, begin: bytes, end: bytes, src_ids: List[str]):
        self.begin = begin
        self.end = end
        self.src_ids = src_ids
        self.phase = AddingShard.FETCHING
        self.buffer: List[Tuple[int, int, Mutation]] = []  # (version, seq, m)
        self.fetch_version = 0
        self.finalized = False  # settling record arrived while still fetching


class StorageServer:
    """In-memory MVCC window, optionally over a durable base engine.

    With `kvstore` set, applied mutations are mirrored into the engine and
    committed on a cadence; the window is trimmed to the durable floor and
    the TLog popped only after durability (ref: updateStorage ->
    IKeyValueStore::commit -> tLogPop).  Without it, applied == durable and
    the log is popped eagerly (the original in-memory slice).
    """

    def __init__(
        self,
        process: SimProcess,
        tlog,  # TLogInterface or List[TLogInterface]
        epoch_begin_version: int = 0,
        kvstore=None,
        storage_id: str = None,
        owned_all: bool = True,
        meta=None,
        n_route_logs: int = None,  # tag placement spans the first N logs
        # (the rest are satellites: in the ack/confirm set, not consumed)
    ):
        self.process = process
        self.tlogs: List[TLogInterface] = (
            list(tlog) if isinstance(tlog, (list, tuple)) else [tlog]
        )
        self.n_route_logs = (
            len(self.tlogs) if n_route_logs is None else n_route_logs
        )
        self.store = VersionedStore()
        self.kvstore = kvstore
        self.storage_id = storage_id or f"ss:{process.machine.machine_id}"
        self.owned = RangeMap(False)
        self.adding = RangeMap(False)  # range -> AddingShard while moving in
        self.avail = RangeMap(0)  # per-range read-version floor (fetch snap)
        # storage id -> StorageInterface, learned from \xff/serverList/
        # mutations in the stream (ref: the serverList system keys).
        self.server_list: Dict[str, StorageInterface] = {}
        self._meta_dirty = True
        if meta is not None:
            owned_entries, avail_entries, server_list, ready_shards = meta
            for b, e, v in owned_entries:
                self.owned.set_range(b, e, v)
            for b, e, v in avail_entries:
                self.avail.set_range(b, e, v)
            self.server_list = dict(server_list)
            # READY AddingShards persist with the same commit that made
            # their fetched data durable, so a crash between FETCHED and the
            # settle record doesn't lose the move (the settle replayed from
            # the log tail finds the shard and flips it).
            for b, e, fv in ready_shards:
                shard = AddingShard(b, e, [])
                shard.phase = AddingShard.READY
                shard.fetch_version = fv
                self.adding.set_range(b, e, shard)
        elif owned_all:
            self.owned.set_range(b"", None, True)
        self.version = NotifiedVersion(epoch_begin_version)
        self.durable_version = epoch_begin_version
        self.byte_sample = ByteSample(process.network.loop.rng)
        # Ratekeeper signals (ref: StorageQueueInfo — bytesInput /
        # bytesDurable; queue depth = input - durable).
        self.input_bytes = 0
        self.durable_bytes = 0
        if kvstore is not None:
            # Rebuild from the durable base after a restart (the reference
            # persists its byte sample for the same reason); paged so huge
            # stores don't need one giant materialization.
            lo = b""
            while True:
                page = kvstore.read_range(lo, KEYSPACE_END, limit=4096)
                for k, v in page:
                    self.byte_sample.update(k, len(k) + len(v))
                if len(page) < 4096:
                    break
                lo = page[-1][0] + b"\x00"
        self._metrics_stream = RequestStream(
            process, "get_storage_metrics", well_known=True
        )
        self._gv_stream = RequestStream(process, "get_value", well_known=True)
        self._gkv_stream = RequestStream(process, "get_key_values", well_known=True)
        self._ver_stream = RequestStream(process, "get_version", well_known=True)
        self._watch_stream = RequestStream(process, "watch_value", well_known=True)
        self._fetch_stream = RequestStream(process, "fetch_shard", well_known=True)
        self._shard_state_stream = RequestStream(
            process, "get_shard_state", well_known=True
        )
        self._owned_meta_stream = RequestStream(
            process, "get_owned_meta", well_known=True
        )
        # key -> [(watched_value, reply)] parked until the key changes
        self._watches: Dict[bytes, list] = {}
        # The logs holding this storage's tag (ref: peek-merge cursors over
        # the tag's tlog subset); broadcast tags live everywhere, so any of
        # these serves the full subscription.
        from .log_system import tlogs_for_tag

        self._my_logs = [
            self.tlogs[i]
            for i in tlogs_for_tag(self.storage_id, self.n_route_logs)
        ]
        self._tags = [self.storage_id, TAG_DEFAULT, TAG_ALL]
        self._kc_cache = epoch_begin_version  # last all-logs-confirmed min
        # Register our consumer floor before anything else runs: the logs
        # must not discard entries this storage hasn't peeked.  Logs we
        # never peek get a vacuous (infinite) floor so this consumer never
        # blocks their trimming.
        my = set(id(t) for t in self._my_logs)
        for tl in self.tlogs:
            tl.pop.send(
                process,
                TLogPopRequest(
                    version=(
                        epoch_begin_version if id(tl) in my else 1 << 60
                    ),
                    tag=self.storage_id,
                ),
            )
        process.spawn(self._update_loop(), "ss_update")
        process.spawn_observed(self._serve_get_value(), "ss_get_value")
        process.spawn_observed(self._serve_metrics(), "ss_metrics")
        process.spawn_observed(self._serve_get_key_values(), "ss_get_key_values")
        process.spawn_observed(self._serve_get_version(), "ss_get_version")
        process.spawn_observed(self._serve_watch_value(), "ss_watch")
        process.spawn_observed(self._serve_fetch_shard(), "ss_fetch")
        process.spawn_observed(self._serve_get_shard_state(), "ss_shard_state")
        process.spawn_observed(self._serve_get_owned_meta(), "ss_owned_meta")

    @classmethod
    async def recover(
        cls,
        process: SimProcess,
        tlog: TLogInterface,
        fs,
        filename: str,
        storage_id: str = None,
        owned_all: bool = True,
        engine: str = "memory",
    ):
        """Reopen the base engine and resume pulling from its durable
        version (ref: storageServer rollback/restart recovery).  Ownership
        is restored from the durable meta record; keyServers mutations in
        the replayed log tail re-apply any later changes.  A move still
        FETCHING at the crash is absent after recovery — DD observes
        "missing" shard state and restarts it.  A move that reached READY
        is durable (persisted with the fetched rows in one commit by
        _finish_fetch) and is restored as a READY AddingShard: the source
        may already have settled and dropped its copy, so re-fetching is
        not an option (the round-5 write-through fix).

        engine: "memory" (WAL+snapshot RAM map, KeyValueStoreMemory.
        actor.cpp analog) or "btree" (COW B+tree, the ssd-class engine —
        datasets exceed RAM; ref KeyValueStoreSQLite.actor.cpp's role)."""
        kv = await open_engine(engine, fs, process, filename)
        vmeta = kv.read_value(VERSION_META_KEY)
        durable = int(vmeta.decode()) if vmeta else 0
        owned_meta = kv.read_value(OWNED_META_KEY)
        meta = decode_frame(owned_meta) if owned_meta else None
        return cls(
            process,
            tlog,
            epoch_begin_version=durable,
            kvstore=kv,
            storage_id=storage_id,
            owned_all=owned_all if meta is None else False,
            meta=meta,
        )

    def interface(self) -> StorageInterface:
        return StorageInterface(
            storage_id=self.storage_id,
            get_storage_metrics=self._metrics_stream.ref(),
            get_value=self._gv_stream.ref(),
            get_key_values=self._gkv_stream.ref(),
            get_version=self._ver_stream.ref(),
            watch_value=self._watch_stream.ref(),
            fetch_shard=self._fetch_stream.ref(),
            get_shard_state=self._shard_state_stream.ref(),
            get_owned_meta=self._owned_meta_stream.ref(),
        )

    # -- watches (ref watchValue_impl storageserver.actor.cpp:760) --
    async def _serve_watch_value(self):
        while True:
            req, reply = await self._watch_stream.pop()
            self.process.spawn(self._watch_one(req, reply), "ss_watch_one")

    async def _watch_one(self, req: WatchValueRequest, reply):
        try:
            self._check_range_owned(req.key, key_after(req.key), req.version)
            await self._wait_for_version(req.version)
            # Ownership may have moved away during the wait; re-check so a
            # disowned (dropped) range re-routes instead of reading as empty.
            self._check_range_owned(req.key, key_after(req.key), req.version)
        except FdbError as e:
            reply.send_error(e.name)
            return
        current = self._get_current(req.key, self.version.get())
        if current != req.value:
            reply.send(self.version.get())  # changed already: fire now
            return
        n_parked = sum(len(v) for v in self._watches.values())
        if n_parked >= g_knobs.server.max_watches:
            reply.send_error("too_many_watches")
            return
        self._watches.setdefault(req.key, []).append((req.value, reply))

    def _check_watches(self, version: int, touched_keys, cleared_ranges):
        """Called after applying a version's mutations: fire watches whose
        key changed value."""
        if not self._watches:
            return
        candidates = set()
        for k in self._watches:
            if k in touched_keys:
                candidates.add(k)
            else:
                for b, e in cleared_ranges:
                    if b <= k < e:
                        candidates.add(k)
                        break
        for k in candidates:
            still = []
            for watched_value, reply in self._watches.get(k, []):
                now_val = self._get_current(k, version)
                if now_val != watched_value:
                    reply.send(version)
                else:
                    still.append((watched_value, reply))
            if still:
                self._watches[k] = still
            else:
                self._watches.pop(k, None)

    def _pop_all(self, version: int):
        for tl in self._my_logs:
            tl.pop.send(
                self.process,
                TLogPopRequest(version=version, tag=self.storage_id),
            )

    async def _known_committed_bound(self, reply) -> int:
        """Highest version safe to APPLY (ref: knownCommittedVersion).
        Commits ack only after EVERY log fsyncs, and epoch-end recovery
        truncates above min(all durables) — so a version is safe once
        (a) the proxy has seen it fully acked (rides the pushes), or
        (b) ALL logs (not just our tag's subset: the recovery cut spans
        every log) confirm it durable.  The confirm fan-out is skipped
        while a previous round already covers the log's tail."""
        bound = reply.known_committed
        if len(self.tlogs) == 1:
            return max(bound, reply.end_version)
        best = max(bound, self._kc_cache)
        if reply.end_version <= best:
            return best  # nothing new to confirm
        from ..flow.eventloop import wait_for_all

        try:
            # One concurrent round — serial probes would multiply catch-up
            # latency by the log count.
            durables = await wait_for_all(
                [
                    tl.confirm.get_reply(self.process, None)
                    for tl in self.tlogs
                ]
            )
        except FdbError:
            return best  # a log is unreachable: only (a) is safe
        m = min(durables)
        if m > self._kc_cache:
            self._kc_cache = m
        return max(bound, self._kc_cache)

    # -- write path: pull from the log (ref: storageserver update() via a
    # peek cursor; failover across the tag's log replicas) --
    async def _update_loop(self):
        from ..flow.buggify import buggify

        loop = self.process.network.loop
        last_durable_commit = loop.now()
        log_i = 0
        while True:
            if buggify("storage_apply_lag"):
                # BUGGIFY: a lagging storage — exercises waitForVersion
                # waits, future_version timeouts, and ratekeeper lag paths.
                await loop.delay(loop.rng.random01() * 0.05)
            try:
                reply = await self._my_logs[
                    log_i % len(self._my_logs)
                ].peek.get_reply(
                    self.process,
                    TLogPeekRequest(
                        begin_version=self.version.get(), tags=self._tags
                    ),
                )
            except FdbError:
                # This replica is down: rotate to another log holding our
                # tag (ref: ServerPeekCursor bestServer failover).
                from ..flow.testprobe import test_probe

                test_probe("storage_peek_failover")
                log_i += 1
                await loop.delay(0.05)
                continue
            bound = await self._known_committed_bound(reply)
            for version, mutations in reply.entries:
                if version <= self.version.get():
                    continue
                if version > bound:
                    break  # not yet known-committed; re-peek later
                self._apply(version, mutations)
                self.version.set(version)
            # Advance through tag-empty versions, but never past what this
            # peek actually covered (a limit-truncated peek may end below
            # the known-committed watermark).
            floor = min(bound, reply.end_version)
            if floor > self.version.get():
                self.version.set(floor)
            if self.kvstore is None:
                # In-memory engine: every version stays in the RAM window,
                # so only the MVCC-window floor limits old reads (ref: the
                # 5s window, oldestVersion = version - MAX_WRITE_TRANSACTION
                # _LIFE_VERSIONS); the log still pops eagerly.
                self.durable_version = max(
                    self.durable_version,
                    self.version.get()
                    - g_knobs.server.max_write_transaction_life_versions,
                )
                self.durable_bytes = self.input_bytes  # RAM window IS durable
                self._pop_all(self.version.get())
            elif (
                (
                    loop.now() - last_durable_commit
                    >= g_knobs.server.storage_durability_lag
                    # BUGGIFY: eager durability — trims the MVCC window as
                    # aggressively as possible (transaction_too_old paths).
                    or buggify("storage_eager_durable")
                )
                and self.version.get() > self.durable_version
            ):
                await self._make_durable()
                last_durable_commit = loop.now()
            if not reply.has_more:
                await loop.delay(0.001)  # poll; push-based peek comes later

    async def _make_durable(self):
        """Fold window mutations through the applied version into the base
        engine in (version, seq) order, commit, trim, pop the log (ref:
        updateStorage storageserver.actor.cpp).

        The durable floor is raised BEFORE the engine's RAM state is
        mutated: reads below the new floor error transaction_too_old instead
        of falling through the window to a base engine that is already ahead
        of their version (the fold + commit spans awaits).

        The fold stops an MVCC window short of the applied version (ref:
        storageserver keeping the newest ~5s in the versioned window;
        oldestVersion trails by MAX_WRITE_TRANSACTION_LIFE_VERSIONS) so
        reads at any version the resolver would still admit keep working —
        durability of the recent tail is the log's job until it is popped
        here."""
        new_durable = max(
            self.durable_version,
            self.version.get()
            - g_knobs.server.max_write_transaction_life_versions,
        )
        if new_durable <= self.durable_version:
            # No fold progress, but OWNERSHIP changes must not wait for
            # the version window to advance: a crash after a shard
            # handoff (fetch WRITE-THROUGH already made the data durable)
            # would otherwise recover a server whose durable meta never
            # claimed the shard — unreachable data (round-5 review).
            if self._meta_dirty:
                self._persist_meta_locked()
                await self.kvstore.commit()
            return
        self.durable_version = new_durable
        ops = []
        for key, chain in self.store.kv.items():
            for ver, seq, val in chain:
                if ver <= new_durable:
                    ops.append((ver, seq, "set", key, val))
        for ver, seq, b, e in self.store.clears:
            if ver <= new_durable:
                ops.append((ver, seq, "clear", b, e))
        ops.sort(key=lambda o: (o[0], o[1]))
        for _v, _s, op, a, b in ops:
            self.durable_bytes += len(a) + len(b) + 16
            if op == "set":
                self.kvstore.set(a, b)
            else:
                self.kvstore.clear_range(a, b)
        self.kvstore.set(VERSION_META_KEY, b"%d" % new_durable)
        if self._meta_dirty:
            self._persist_meta_locked()
        await self.kvstore.commit()
        self.store.trim(new_durable)
        self._pop_all(new_durable)

    def _persist_meta_locked(self):
        """Serialize ownership/avail/serverList/READY-shard meta into the
        engine's write buffer (caller commits)."""
        self._meta_dirty = False
        ready = {
            id(a): a for _b, _e, a in self.adding.items()
            if a and a.phase == AddingShard.READY
        }
        meta = (
            [(b, e, v) for b, e, v in self.owned.items()],
            [(b, e, v) for b, e, v in self.avail.items()],
            dict(self.server_list),
            [(a.begin, a.end, a.fetch_version) for a in ready.values()],
        )
        self.kvstore.set(OWNED_META_KEY, encode_frame(meta))

    @property
    def queue_bytes(self) -> int:
        """Un-durable window depth (ref: StorageQueueInfo's
        bytesInput - bytesDurable, the ratekeeper's storage signal)."""
        return max(0, self.input_bytes - self.durable_bytes)

    def _get_current(self, key: bytes, version: int) -> Optional[bytes]:
        touched, val = self.store.get_stamped(key, version)
        if not touched and self.kvstore is not None:
            return self.kvstore.read_value(key)
        return val

    # -- mutation application + metadata interception --
    def _apply(self, version: int, mutations: List[Mutation]):
        touched, cleared = set(), []
        for seq, m in enumerate(mutations):
            # Metadata interception first (ref ApplyMetadataMutation.h):
            # every storage watches keyServers/serverList changes regardless
            # of ownership — that is how shard handoffs reach it, serialized
            # with the stream at this exact version.
            self._apply_metadata(m, version)
            self._route_mutation(m, version, seq, touched, cleared)
        self._check_watches(version, touched, cleared)

    def _route_mutation(self, m: Mutation, version: int, seq: int,
                        touched: set, cleared: list):
        """Apply to owned ranges; buffer into FETCHING AddingShards; apply
        directly into READY ones; drop the rest."""
        if m.type == MutationType.CLEAR_RANGE:
            for cb, ce, v in self.owned.intersecting(m.param1, m.param2):
                ce = m.param2 if ce is None else ce
                if v:
                    self.store.clear_range(cb, ce, version, seq)
                    self.input_bytes += len(cb) + len(ce) + 16
                    self.byte_sample.remove_range(cb, ce)
                    cleared.append((cb, ce))
                    continue
                for ab, ae, shard in self.adding.intersecting(cb, ce):
                    if not shard:
                        continue
                    ae = ce if ae is None else ae
                    clip = Mutation(MutationType.CLEAR_RANGE, ab, ae)
                    if shard.phase == AddingShard.FETCHING:
                        shard.buffer.append((version, seq, clip))
                    else:
                        self.store.clear_range(ab, ae, version, seq)
                        self.input_bytes += len(ab) + len(ae) + 16
                        self.byte_sample.remove_range(ab, ae)
            return
        if m.type in (MutationType.NO_OP, MutationType.DEBUG_KEY):
            return
        key = m.param1
        if self.owned[key]:
            self._apply_point(m, version, seq)
            touched.add(key)
            return
        shard = self.adding[key]
        if shard:
            if shard.phase == AddingShard.FETCHING:
                shard.buffer.append((version, seq, m))
            else:
                self._apply_point(m, version, seq)

    def _apply_point(self, m: Mutation, version: int, seq: int):
        if m.type == MutationType.SET_VALUE:
            self.store.set(m.param1, m.param2, version, seq)
            val = m.param2
        else:
            existing = self._get_current(m.param1, version)
            val = apply_atomic(m.type, existing, m.param2)
            self.store.set(m.param1, val, version, seq)
        # Ratekeeper input accounting: count exactly what enters the
        # window (what _make_durable later folds out), so queue_bytes =
        # input - durable measures the REAL un-durable depth.
        self.input_bytes += len(m.param1) + len(val or b"") + 16
        if m.param1 < KEYSPACE_END:
            self.byte_sample.update(m.param1, len(m.param1) + len(val or b""))

    def _apply_metadata(self, m: Mutation, version: int):
        from .system_keys import parse_metadata_mutation

        parsed = parse_metadata_mutation(m)
        if parsed is None:
            return
        if parsed[0] == "server":
            _kind, sid, iface = parsed
            self.server_list[sid] = iface
            self._meta_dirty = True
        elif parsed[0] == "resolver_split":
            pass  # proxy-side concern; storages don't partition resolution
        elif parsed[0] == "lock":
            pass  # lock enforcement lives at the proxies
        else:
            self._meta_dirty = True
            _kind, begin, src, dest, end = parsed
            if dest:
                self._start_adding(begin, end, src, dest, version)
            else:
                self._finish_shard(begin, end, src, version)

    def _start_adding(self, begin: bytes, end: bytes, src: List[str],
                      dest: List[str], version: int):
        """A move src -> dest began at `version`.  Sources keep serving
        reads until the settling record; a destination that lacks the data
        starts an AddingShard fetch (ref: startMoveKeys writing dest into
        keyServers, MoveKeys.actor.cpp)."""
        if end is None:
            # The CC seeds the tail keyServers record open-ended; every
            # byte-comparison downstream (clear_range, fetch paging, the
            # byte sample) needs a concrete bound or a move of the TAIL
            # shard dies in a TypeError and wedges FETCHING forever.
            end = KEYSPACE_END
        if self.storage_id not in dest or self.storage_id in src:
            return
        if all(v for _b, _e, v in self.owned.intersecting(begin, end)):
            return  # already fully own it
        overlapping = {
            id(a): a for _b, _e, a in self.adding.intersecting(begin, end) if a
        }
        if len(overlapping) == 1:
            a = next(iter(overlapping.values()))
            if a.begin == begin and a.end == end:
                return  # duplicate record (DD retry); fetch already running
        # A different overlapping move supersedes: cancel the old shards over
        # their FULL extents (their fetch actors notice and abort; any piece
        # outside [begin,end) becomes "missing" and DD restarts it).
        for a in overlapping.values():
            self.adding.set_range(a.begin, a.end, False)
            self.owned.set_range(a.begin, a.end, False)
        shard = AddingShard(begin, end, [s for s in src if s != self.storage_id])
        self.owned.set_range(begin, end, False)
        self.adding.set_range(begin, end, shard)
        if not shard.src_ids:
            # Brand-new (empty) shard: nothing to fetch.
            shard.fetch_version = version
            shard.phase = AddingShard.READY
        else:
            self.process.spawn(self._fetch_shard_data(shard), "ss_fetch_data")

    def _finish_shard(self, begin: bytes, end: bytes, team: List[str],
                      version: int):
        """A settling record: [begin, end) now belongs to `team` (ref:
        finishMoveKeys flipping serverKeys).  Non-members disown and drop;
        members flip their AddingShard live (or adopt an empty new shard)."""
        if self.storage_id not in team:
            self._disown(begin, end)
            return
        shards = {id(a): a for _b, _e, a in self.adding.intersecting(begin, end)
                  if a}
        for a in shards.values():
            if a.phase == AddingShard.READY:
                self._flip_to_owned(a)
            else:
                # Fetch still in flight (only possible if DD restarted and
                # re-settled blindly): flip when the data completes.
                a.finalized = True
        # NOTE: an unowned sub-range with no AddingShard here stays unowned
        # ("missing") — e.g. an in-flight move lost across a crash.  Adopting
        # it empty would turn data loss into a readable empty shard; instead
        # DD observes "missing" via get_shard_state and restarts the move.
        # Seeding a brand-new shard uses a (src=[], dest=team) record (which
        # creates an empty READY AddingShard) followed by a settle.

    def _flip_to_owned(self, shard: AddingShard):
        self.adding.set_range(shard.begin, shard.end, False)
        self.owned.set_range(shard.begin, shard.end, True)
        self.avail.set_range(shard.begin, shard.end, shard.fetch_version)
        self._meta_dirty = True

    def _disown(self, begin: bytes, end: bytes):
        had = any(v for _b, _e, v in self.owned.intersecting(begin, end))
        self.owned.set_range(begin, end, False)
        self.adding.set_range(begin, end, False)
        self._meta_dirty = True
        if had:
            self._drop_range(begin, end)

    def _drop_range(self, begin: bytes, end: bytes):
        """Evict data for a range this server no longer owns; parked watches
        in the range fire wrong_shard_server so clients re-route."""
        hi = min(end, KEYSPACE_END) if end is not None else KEYSPACE_END
        self.byte_sample.remove_range(begin, hi)
        if self.kvstore is not None:
            self.kvstore.clear_range(begin, hi)
        i = bisect_left(self.store.sorted_keys, begin)
        j = bisect_left(self.store.sorted_keys, hi)
        for k in self.store.sorted_keys[i:j]:
            self.store.kv.pop(k, None)
        del self.store.sorted_keys[i:j]
        for k in [k for k in self._watches if begin <= k < hi]:
            for _val, reply in self._watches.pop(k):
                reply.send_error("wrong_shard_server")

    # -- shard fetch: destination side (ref fetchKeys storageserver :85-133) --
    async def _fetch_shard_data(self, shard: AddingShard):
        loop = self.process.network.loop
        attempt = 0
        while True:
            if self.adding[shard.begin] is not shard:
                return  # move cancelled or superseded
            srcs = [self.server_list.get(s) for s in shard.src_ids]
            srcs = [s for s in srcs if s is not None]
            if not srcs:
                await loop.delay(0.05)  # serverList entry not yet seen
                continue
            src = srcs[attempt % len(srcs)]
            attempt += 1
            snap = self.version.get()
            try:
                await self._fetch_pages(shard, src, snap)
                break
            except FdbError:
                # Source dead / snapshot aged out of its window / it no
                # longer owns the range: back off and retry at a newer
                # snapshot (ref: fetchKeys' transaction_too_old retry).
                await loop.delay(0.05)
        if self.adding[shard.begin] is not shard:
            return
        # Replay the buffered tail the snapshot missed, in stream order.
        for ver, seq, m in shard.buffer:
            if ver <= shard.fetch_version:
                continue
            if m.type == MutationType.CLEAR_RANGE:
                self.store.clear_range(m.param1, m.param2, ver, seq)
                self.input_bytes += len(m.param1) + len(m.param2) + 16
                self.byte_sample.remove_range(m.param1, m.param2)
            else:
                self._apply_point(m, ver, seq)
        shard.buffer = []
        shard.phase = AddingShard.READY
        self._meta_dirty = True
        if self.kvstore is not None:
            # One commit covers the written-through rows AND the READY
            # claim: after this fsync a crashed destination recovers the
            # shard complete (the settle's flip persists via the next
            # meta-only durability pass).
            self._persist_meta_locked()
            await self.kvstore.commit()
        if shard.finalized:
            self._flip_to_owned(shard)

    async def _fetch_pages(self, shard: AddingShard, src: StorageInterface,
                           snap: int):
        """Stream the shard at one fixed snapshot version.  A clear at the
        snapshot resets any partial previous attempt (it sorts below the
        page's sets at the same version), so retries at newer snapshots
        converge."""
        self.store.clear_range(shard.begin, shard.end, snap, 0)
        self.input_bytes += len(shard.begin) + len(shard.end) + 16
        self.byte_sample.remove_range(shard.begin, shard.end)
        # WRITE-THROUGH: fetched rows go straight into the durable base
        # engine too, fsynced before the shard can report READY.  The
        # settle that follows READY makes the SOURCE durably drop its
        # copy, so a destination holding the snapshot only in its RAM
        # window would leave the data existing NOWHERE durable across a
        # crash (snapshots never ride the log) — silent loss (ref:
        # fetchKeys persisting fetched data before the shard turns
        # readable, storageserver.actor.cpp fetchKeys).  Base rows above
        # durable_version are benign: window entries shadow them until
        # trim, and recovery gates reads with the avail floor (= snap).
        if self.kvstore is not None:
            self.kvstore.clear_range(shard.begin, shard.end)
        begin = shard.begin
        while True:
            rep: FetchShardReply = await src.fetch_shard.get_reply(
                self.process,
                FetchShardRequest(begin=begin, end=shard.end, version=snap),
            )
            if self.adding[shard.begin] is not shard:
                from ..flow.testprobe import test_probe

                test_probe("fetch_superseded")
                # Superseded mid-page by an overlapping move: STOP writing
                # through — the new fetch's clear_range/sets share the
                # base-engine commit buffer, and a stale row written after
                # it would win last-writer-wins durably (served after a
                # crash even though the RAM window shadows it).  The
                # caller's top-of-loop check turns this into a return.
                raise FdbError("fetch_superseded")
            for k, v in rep.data:
                self.store.set(k, v, snap, 1)
                if self.kvstore is not None:
                    self.kvstore.set(k, v)
                self.input_bytes += len(k) + len(v) + 16
                self.byte_sample.update(k, len(k) + len(v))
            if not rep.more:
                break
            begin = key_after(rep.data[-1][0])
        shard.fetch_version = snap

    # -- shard fetch: source side --
    async def _serve_fetch_shard(self):
        while True:
            req, reply = await self._fetch_stream.pop()
            self.process.spawn(self._fetch_shard_one(req, reply), "ss_fetch_one")

    async def _fetch_shard_one(self, req: FetchShardRequest, reply):
        try:
            await self._wait_for_version(req.version)
        except FdbError as e:
            reply.send_error(e.name)
            return
        if not all(
            v for _b, _e, v in self.owned.intersecting(req.begin, req.end)
        ):
            reply.send_error("wrong_shard_server")
            return
        page = g_knobs.server.fetch_shard_page_rows
        data = self._range_at(req.begin, req.end, req.version, page + 1, False)
        reply.send(
            FetchShardReply(data=data[:page], version=req.version,
                            more=len(data) > page)
        )

    async def _serve_get_owned_meta(self):
        while True:
            req, reply = await self._owned_meta_stream.pop()
            self.process.spawn_observed(self._owned_meta_one(req, reply), "ss_om_one")

    async def _owned_meta_one(self, req, reply):
        # Answer only once the replayed log tail (with any settled handoffs)
        # is applied, so the recovered routing map is not stale.
        await self.version.when_at_least(req.min_version)
        reply.send(
            (
                self.storage_id,
                [(b, e) for b, e, v in self.owned.items() if v],
                dict(self.server_list),
            )
        )

    async def _serve_get_shard_state(self):
        while True:
            req, reply = await self._shard_state_stream.pop()
            reply.send(self._shard_state(req))

    def _shard_state(self, req: GetShardStateRequest) -> str:
        states = set()
        for b, e, v in self.owned.intersecting(req.begin, req.end):
            if v:
                states.add("readable")
                continue
            e2 = req.end if e is None else e
            adds = [a for _ab, _ae, a in self.adding.intersecting(b, e2) if a]
            if not adds:
                states.add("missing")
            else:
                states.update(
                    "fetched" if a.phase == AddingShard.READY else "adding"
                    for a in adds
                )
        for s in ("missing", "adding", "fetched"):
            if s in states:
                return s
        return "readable"

    # -- read path --
    def _check_range_owned(self, begin: bytes, end: bytes, version: int):
        """Reject reads this server can't answer: outside owned ranges ->
        wrong_shard_server (client re-routes); below a fetched shard's
        snapshot floor -> transaction_too_old (ref: getShardState /
        waitForVersion interplay in storageserver read paths)."""
        for _b, _e, v in self.owned.intersecting(begin, end):
            if not v:
                raise FdbError("wrong_shard_server")
        floor = 0
        for _b, _e, v in self.avail.intersecting(begin, end):
            floor = max(floor, v)
        if version < floor:
            raise FdbError("transaction_too_old")

    async def _wait_for_version(self, version: int):
        """Ref: waitForVersion storageserver.actor.cpp:631."""
        if version > self.version.get() + g_knobs.server.max_versions_in_flight:
            raise FdbError("future_version")
        if version < self.durable_version:
            # The window below the durable floor is gone (ref: reads below
            # oldestVersion -> transaction_too_old, storageserver :640).
            raise FdbError("transaction_too_old")
        if self.version.get() < version:
            # Bounded wait: if this server's log stream has stalled (tlog
            # dead, generation ending), fail the read instead of parking
            # forever — the client retries with a fresh version against the
            # next generation (ref: the FUTURE_VERSION_DELAY timeout in
            # waitForVersion throwing future_version, storageserver :631).
            from ..flow.eventloop import timeout_after

            got = await timeout_after(
                self.process.network.loop,
                self.version.when_at_least(version),
                g_knobs.server.future_version_delay,
                default=None,
            )
            if got is None and self.version.get() < version:
                raise FdbError("future_version")
        if version < self.durable_version:  # floor may have risen across the wait
            raise FdbError("transaction_too_old")

    async def _serve_get_value(self):
        while True:
            req, reply = await self._gv_stream.pop()
            self.process.spawn(self._get_value_one(req, reply), "ss_gv")

    async def _get_value_one(self, req: GetValueRequest, reply):
        try:
            self._check_range_owned(req.key, key_after(req.key), req.version)
            await self._wait_for_version(req.version)
            self._check_range_owned(req.key, key_after(req.key), req.version)
        except FdbError as e:
            reply.send_error(e.name)
            return
        reply.send(
            GetValueReply(
                value=self._get_current(req.key, req.version), version=req.version
            )
        )

    async def _serve_get_key_values(self):
        while True:
            req, reply = await self._gkv_stream.pop()
            self.process.spawn(self._get_key_values_one(req, reply), "ss_gkv")

    async def _get_key_values_one(self, req: GetKeyValuesRequest, reply):
        try:
            self._check_range_owned(req.begin, req.end, req.version)
            await self._wait_for_version(req.version)
            self._check_range_owned(req.begin, req.end, req.version)
        except FdbError as e:
            reply.send_error(e.name)
            return
        data = self._range_at(
            req.begin, req.end, req.version, req.limit + 1, req.reverse
        )
        more = len(data) > req.limit
        reply.send(
            GetKeyValuesReply(data=data[: req.limit], more=more, version=req.version)
        )

    def _range_at(self, begin, end, version, limit, reverse):
        """Window-over-base merged range read (window clears mask base keys).

        Two-pointer merge over the already-sorted base and window key lists
        with early exit, so a limited read costs O(limit + skipped-masked),
        not O(range size).
        """
        if self.kvstore is None:
            return self.store.get_range(begin, end, version, limit, reverse)
        # Base keys arrive in PAGES through the engine-neutral
        # read_keys_page (works for the Python memory engine and the
        # native C++ engine alike), merged against the window's sorted
        # keys; window clears mask base rows, so more pages are pulled
        # until `limit` merged rows exist or the base is exhausted.
        wkeys = self.store.sorted_keys
        wi = bisect_left(wkeys, begin)
        wj = bisect_left(wkeys, end)
        # Window keys are indexed in place (no range-sized slice/reverse):
        # a limited read stays O(limit + masked keys skipped).
        if reverse:
            iw, ew, wstep = wj - 1, wi - 1, -1
        else:
            iw, ew, wstep = wi, wj, 1
        before = (lambda x, y: x > y) if reverse else (lambda x, y: x < y)
        rows: list = []
        page_lo, page_hi = begin, end
        page: list = []
        ia = 0
        exhausted = False
        while len(rows) < limit:
            if ia >= len(page) and not exhausted:
                page = self.kvstore.read_keys_page(
                    page_lo, page_hi, max(limit, 256), reverse
                )
                ia = 0
                if len(page) < max(limit, 256):
                    exhausted = True
                elif reverse:
                    page_hi = page[-1]  # next page strictly below
                else:
                    page_lo = page[-1] + b"\x00"
            ka = page[ia] if ia < len(page) else None
            kb = wkeys[iw] if iw != ew else None
            if ka is None and kb is None:
                break
            if kb is None or (ka is not None and before(ka, kb)):
                k = ka
                ia += 1
            elif ka is None or before(kb, ka):
                k = kb
                iw += wstep
            else:  # same key in both
                k = ka
                ia += 1
                iw += wstep
            touched, wv = self.store.get_stamped(k, version)
            v = wv if touched else self.kvstore.read_value(k)
            if v is not None:
                rows.append((k, v))
        return rows

    async def _serve_metrics(self):
        """Byte estimates + split points for DD (ref: waitMetrics /
        splitMetrics served from the byte sample)."""
        from .interfaces import GetStorageMetricsReply

        while True:
            req, reply = await self._metrics_stream.pop()
            if getattr(req, "signals_only", False):
                reply.send(
                    GetStorageMetricsReply(
                        version=self.version.get(),
                        queue_bytes=self.queue_bytes,
                    )
                )
                continue
            end = req.end if req.end != b"" else None
            reply.send(
                GetStorageMetricsReply(
                    bytes=self.byte_sample.bytes_in(req.begin, end),
                    split_key=self.byte_sample.split_point(req.begin, end),
                    version=self.version.get(),
                    queue_bytes=self.queue_bytes,
                )
            )

    async def _serve_get_version(self):
        while True:
            _req, reply = await self._ver_stream.pop()
            reply.send(self.version.get())
