"""Storage server role: versioned MVCC reads over pulled log data.

Ref: storageserver.actor.cpp — VersionedData :236-260 (MVCC window),
getValueQ :684 / getKeyValues :1182 read path with waitForVersion :631;
update() pulls mutations from the log via peek and applies them in version
order; atomics are applied at the storage server exactly as the client
would (shared fdbclient/Atomic.h semantics -> client/atomic.py).

v1 model: per-key version chains + a version-stamped clear-range list; one
storage process owns the whole key space (sharding arrives with
DataDistribution).  All history is retained in-memory; the durability
milestone adds the persistent engine + window trimming.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right, insort
from typing import Dict, List, Optional, Tuple

from ..client.atomic import apply_atomic
from ..client.types import Mutation, MutationType
from ..flow.asyncvar import NotifiedVersion
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from .interfaces import (
    GetKeyValuesReply,
    GetKeyValuesRequest,
    GetValueReply,
    GetValueRequest,
    StorageInterface,
    TLogInterface,
    TLogPeekRequest,
    TLogPopRequest,
    WatchValueRequest,
)


class VersionedStore:
    """Per-key version chains + clear-range history (the flat-python stand-in
    for the reference's PTree VersionedMap, fdbclient/VersionedMap.h:43).

    Entries are ordered by (version, seq) where seq is the mutation's index
    within its version, so set-then-clear vs clear-then-set of the same key
    inside one commit resolve exactly as the mutation order says.
    """

    _SEQ_INF = 1 << 62

    def __init__(self):
        # key -> [(version, seq, value-or-None)]
        self.kv: Dict[bytes, List[Tuple[int, int, Optional[bytes]]]] = {}
        self.sorted_keys: List[bytes] = []
        # (version, seq, begin, end)
        self.clears: List[Tuple[int, int, bytes, bytes]] = []

    # -- reads --
    def _latest_clear_over(self, key: bytes, version: int) -> Tuple[int, int]:
        best = (-1, -1)
        for v, s, b, e in self.clears:
            if v <= version and b <= key < e and (v, s) > best:
                best = (v, s)
        return best

    def get_stamped(self, key: bytes, version: int):
        """(touched, value): touched=False means no window entry covers the
        key at this version (the caller may fall through to a base engine)."""
        chain = self.kv.get(key)
        stamp_e, val = (-1, -1), None
        if chain:
            i = bisect_right(chain, (version, self._SEQ_INF)) - 1
            if i >= 0:
                ver, seq, val = chain[i]
                stamp_e = (ver, seq)
        stamp_c = self._latest_clear_over(key, version)
        if stamp_c > stamp_e:
            return True, None
        if stamp_e == (-1, -1):
            return False, None
        return True, val

    def get(self, key: bytes, version: int) -> Optional[bytes]:
        _touched, val = self.get_stamped(key, version)
        return val

    def trim(self, through_version: int):
        """Drop window state at versions <= through_version (the base engine
        is durable through it; ref: the MVCC window following durability,
        storageserver updateStorage -> setOldestVersion)."""
        for key in list(self.kv):
            chain = [e for e in self.kv[key] if e[0] > through_version]
            if chain:
                self.kv[key] = chain
            else:
                del self.kv[key]
                i = bisect_left(self.sorted_keys, key)
                if i < len(self.sorted_keys) and self.sorted_keys[i] == key:
                    del self.sorted_keys[i]
        self.clears = [c for c in self.clears if c[0] > through_version]

    def get_range(
        self,
        begin: bytes,
        end: bytes,
        version: int,
        limit: int,
        reverse: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        i = bisect_left(self.sorted_keys, begin)
        j = bisect_left(self.sorted_keys, end)
        keys = self.sorted_keys[i:j]
        if reverse:
            keys = reversed(keys)
        out = []
        for k in keys:
            v = self.get(k, version)
            if v is not None:
                out.append((k, v))
                if len(out) >= limit:
                    break
        return out

    # -- writes (applied in (version, seq) order by the update loop) --
    def set(self, key: bytes, value: bytes, version: int, seq: int = 0):
        chain = self.kv.get(key)
        if chain is None:
            self.kv[key] = [(version, seq, value)]
            insort(self.sorted_keys, key)
        else:
            chain.append((version, seq, value))

    def clear_range(self, begin: bytes, end: bytes, version: int, seq: int = 0):
        self.clears.append((version, seq, begin, end))


VERSION_META_KEY = b"\xff\xffmeta/durable_version"
OWNED_META_KEY = b"\xff\xffmeta/owned_ranges"


class StorageServer:
    """In-memory MVCC window, optionally over a durable base engine.

    With `kvstore` set, applied mutations are mirrored into the engine and
    committed on a cadence; the window is trimmed to the durable floor and
    the TLog popped only after durability (ref: updateStorage ->
    IKeyValueStore::commit -> tLogPop).  Without it, applied == durable and
    the log is popped eagerly (the original in-memory slice).

    Sharding: `owned` maps key ranges this server serves (ref: serverKeys /
    shardsAffectedByTeamFailure).  Ownership changes ride the mutation
    stream itself — every storage intercepts `\xff/keyServers/` mutations
    (ApplyMetadataMutation analog) so a shard handoff happens at an exact
    commit version on every role that watches the stream.  A range being
    fetched (`adding`) applies mutations but does not serve reads (ref:
    AddingShard, storageserver.actor.cpp:85-133).  Reads outside owned
    ranges fail with wrong_shard_server (the client invalidates its
    location cache and retries).  Ownership is persisted with the durable
    snapshot and recovered before log replay.
    """

    def __init__(
        self,
        process: SimProcess,
        tlog: TLogInterface,
        epoch_begin_version: int = 0,
        kvstore=None,
        storage_id: str = None,
        owned_all: bool = True,
        owned_ranges: list = None,
    ):
        from ..utils import RangeMap

        self.process = process
        self.tlog = tlog
        self.store = VersionedStore()
        self.kvstore = kvstore
        self.storage_id = storage_id or f"ss:{process.machine.machine_id}"
        self.owned = RangeMap(False)
        if owned_ranges is not None:
            for b, e in owned_ranges:
                self.owned.set_range(b, e, True)
        elif owned_all:
            self.owned.set_range(b"", None, True)
        self.adding = RangeMap(False)
        self.version = NotifiedVersion(epoch_begin_version)
        self.durable_version = epoch_begin_version
        self._gv_stream = RequestStream(process, "get_value", well_known=True)
        self._gkv_stream = RequestStream(process, "get_key_values", well_known=True)
        self._ver_stream = RequestStream(process, "get_version", well_known=True)
        self._watch_stream = RequestStream(process, "watch_value", well_known=True)
        self._fetch_stream = RequestStream(process, "fetch_shard", well_known=True)
        # key -> [(watched_value, reply)] parked until the key changes
        self._watches: Dict[bytes, list] = {}
        process.spawn(self._update_loop(), "ss_update")
        process.spawn(self._serve_get_value(), "ss_get_value")
        process.spawn(self._serve_get_key_values(), "ss_get_key_values")
        process.spawn(self._serve_get_version(), "ss_get_version")
        process.spawn(self._serve_watch_value(), "ss_watch")
        process.spawn(self._serve_fetch_shard(), "ss_fetch")

    @classmethod
    async def recover(
        cls,
        process: SimProcess,
        tlog: TLogInterface,
        fs,
        filename: str,
        storage_id: str = None,
        owned_all: bool = True,
    ):
        """Reopen the base engine and resume pulling from its durable
        version (ref: storageServer rollback/restart recovery).  Ownership
        is restored from the durable meta record; keyServers mutations in
        the replayed log tail re-apply any later changes."""
        import pickle

        from ..fileio.kvstore import KeyValueStoreMemory

        kv = await KeyValueStoreMemory.open(fs, process, filename)
        meta = kv.read_value(VERSION_META_KEY)
        durable = int(meta.decode()) if meta else 0
        owned_meta = kv.read_value(OWNED_META_KEY)
        owned_ranges = pickle.loads(owned_meta) if owned_meta else None
        return cls(
            process,
            tlog,
            epoch_begin_version=durable,
            kvstore=kv,
            storage_id=storage_id,
            owned_all=owned_all if owned_meta is None else False,
            owned_ranges=owned_ranges,
        )

    def interface(self) -> StorageInterface:
        return StorageInterface(
            storage_id=self.storage_id,
            get_value=self._gv_stream.ref(),
            get_key_values=self._gkv_stream.ref(),
            get_version=self._ver_stream.ref(),
            watch_value=self._watch_stream.ref(),
            fetch_shard=self._fetch_stream.ref(),
        )

    # -- watches (ref watchValue_impl storageserver.actor.cpp:760) --
    async def _serve_watch_value(self):
        while True:
            req, reply = await self._watch_stream.pop()
            self.process.spawn(self._watch_one(req, reply), "ss_watch_one")

    async def _watch_one(self, req: WatchValueRequest, reply):
        from ..flow.knobs import g_knobs

        try:
            await self._wait_for_version(req.version)
        except Exception as e:  # noqa: BLE001
            reply.send_error(getattr(e, "name", "internal_error"))
            return
        current = self._get_current(req.key, self.version.get())
        if current != req.value:
            reply.send(self.version.get())  # changed already: fire now
            return
        n_parked = sum(len(v) for v in self._watches.values())
        if n_parked >= g_knobs.server.max_watches:
            reply.send_error("too_many_watches")
            return
        self._watches.setdefault(req.key, []).append((req.value, reply))

    def _check_watches(self, version: int, touched_keys, cleared_ranges):
        """Called after applying a version's mutations: fire watches whose
        key changed value."""
        if not self._watches:
            return
        candidates = set()
        for k in self._watches:
            if k in touched_keys:
                candidates.add(k)
            else:
                for b, e in cleared_ranges:
                    if b <= k < e:
                        candidates.add(k)
                        break
        for k in candidates:
            still = []
            for watched_value, reply in self._watches.get(k, []):
                now_val = self._get_current(k, version)
                if now_val != watched_value:
                    reply.send(version)
                else:
                    still.append((watched_value, reply))
            if still:
                self._watches[k] = still
            else:
                self._watches.pop(k, None)

    # -- write path: pull from the log (ref: storageserver update()) --
    async def _update_loop(self):
        from ..rpc.stream import retry_get_reply

        loop = self.process.network.loop
        last_durable_commit = loop.now()
        while True:
            reply = await retry_get_reply(
                self.tlog.peek,
                self.process,
                TLogPeekRequest(begin_version=self.version.get()),
            )
            for version, mutations in reply.entries:
                if version <= self.version.get():
                    continue
                self._apply(version, mutations)
                self.version.set(version)
            if self.kvstore is None:
                # In-memory engine: applied == durable, pop eagerly.
                self.durable_version = self.version.get()
                self.tlog.pop.send(
                    self.process, TLogPopRequest(version=self.version.get())
                )
            elif (
                loop.now() - last_durable_commit
                >= g_knobs.server.storage_durability_lag
                and self.version.get() > self.durable_version
            ):
                await self._make_durable()
                last_durable_commit = loop.now()
            if not reply.has_more:
                await loop.delay(0.001)  # poll; push-based peek comes later

    async def _make_durable(self):
        """Fold window mutations through the applied version into the base
        engine in (version, seq) order, commit, trim, pop the log (ref:
        updateStorage storageserver.actor.cpp).

        The durable floor is raised BEFORE the engine's RAM state is
        mutated: reads below the new floor error transaction_too_old instead
        of falling through the window to a base engine that is already ahead
        of their version (the fold + commit spans awaits)."""
        new_durable = self.version.get()
        self.durable_version = new_durable
        ops = []
        for key, chain in self.store.kv.items():
            for ver, seq, val in chain:
                if ver <= new_durable:
                    ops.append((ver, seq, "set", key, val))
        for ver, seq, b, e in self.store.clears:
            if ver <= new_durable:
                ops.append((ver, seq, "clear", b, e))
        ops.sort(key=lambda o: (o[0], o[1]))
        for _v, _s, op, a, b in ops:
            if op == "set":
                self.kvstore.set(a, b)
            else:
                self.kvstore.clear_range(a, b)
        self.kvstore.set(VERSION_META_KEY, b"%d" % new_durable)
        await self.kvstore.commit()
        self.store.trim(new_durable)
        self.tlog.pop.send(self.process, TLogPopRequest(version=new_durable))

    def _get_current(self, key: bytes, version: int) -> Optional[bytes]:
        touched, val = self.store.get_stamped(key, version)
        if not touched and self.kvstore is not None:
            return self.kvstore.read_value(key)
        return val

    def _apply(self, version: int, mutations: List[Mutation]):
        touched, cleared = set(), []
        for seq, m in enumerate(mutations):
            # Metadata interception first (ref ApplyMetadataMutation.h):
            # every storage watches keyServers changes regardless of
            # ownership — that is how shard handoffs reach them, serialized
            # with the stream at this exact version.
            self._apply_metadata(m, version)
            if not self._applies_here(m):
                continue
            if m.type == MutationType.SET_VALUE:
                self.store.set(m.param1, m.param2, version, seq)
                touched.add(m.param1)
            elif m.type == MutationType.CLEAR_RANGE:
                for cb, ce, _v in list(
                    self._clip_to_applied(m.param1, m.param2)
                ):
                    self.store.clear_range(cb, ce, version, seq)
                    cleared.append((cb, ce))
            elif m.type in (MutationType.NO_OP, MutationType.DEBUG_KEY):
                pass
            else:
                existing = self._get_current(m.param1, version)
                self.store.set(
                    m.param1, apply_atomic(m.type, existing, m.param2), version, seq
                )
                touched.add(m.param1)
        self._check_watches(version, touched, cleared)

    def _applies_here(self, m: Mutation) -> bool:
        """Point mutations: owned-or-adding at the key; clears: any overlap
        (clipped at application)."""
        if m.type == MutationType.CLEAR_RANGE:
            return any(True for _ in self._clip_to_applied(m.param1, m.param2))
        return self.owned[m.param1] or self.adding[m.param1]

    def _clip_to_applied(self, begin: bytes, end: bytes):
        """Sub-ranges of [begin, end) that are owned or being added."""
        for cb, ce, v in self.owned.intersecting(begin, end):
            if v:
                yield cb, ce, v
            else:
                e2 = ce
                for ab, ae, av in self.adding.intersecting(cb, e2):
                    if av:
                        yield ab, ae, av

    def _apply_metadata(self, m: Mutation, version: int):
        from . import system_keys as sk

        if m.type == MutationType.SET_VALUE and m.param1.startswith(
            sk.KEY_SERVERS_PREFIX
        ):
            begin = sk.key_servers_begin(m.param1)
            team = sk.decode_team(m.param2)
            # This entry covers [begin, next keyServers entry).  The full
            # extent is recomputed from the authoritative system keyspace by
            # whoever owns it; for ownership purposes each storage only needs
            # the transition at `begin`: the range [begin, end*) where end*
            # is the next boundary KNOWN LOCALLY.  The proxy always writes
            # boundary pairs (begin and end entries) in one commit, so local
            # knowledge is complete for the affected span.
            ends = [
                b
                for b, _e, v in self.owned.items()
                if b > begin and v is not None
            ]
            mine = self.storage_id in team
            end = self._pending_shard_end
            if end is not None and end > begin:
                if mine:
                    self.owned.set_range(begin, end, True)
                    self.adding.set_range(begin, end, False)
                else:
                    self._disown(begin, end)
            self._pending_shard_end = None

    _pending_shard_end = None

    def _disown(self, begin: bytes, end):
        had = any(v for _b, _e, v in self.owned.intersecting(begin, end))
        self.owned.set_range(begin, end, False)
        self.adding.set_range(begin, end, False)
        if had:
            self._drop_range(begin, end)

    def _drop_range(self, begin: bytes, end):
        """Evict data for a range this server no longer owns; parked watches
        in the range fire wrong_shard_server so clients re-route."""
        hi = end if end is not None else b"\xff\xff\xff\xff"
        if self.kvstore is not None:
            self.kvstore.clear_range(begin, hi)
        i = bisect_left(self.store.sorted_keys, begin)
        j = bisect_left(self.store.sorted_keys, hi)
        for k in self.store.sorted_keys[i:j]:
            self.store.kv.pop(k, None)
        del self.store.sorted_keys[i:j]
        for k in [k for k in self._watches if begin <= k < hi]:
            for _val, reply in self._watches.pop(k):
                reply.send_error("wrong_shard_server")

    # -- read path --
    async def _wait_for_version(self, version: int):
        """Ref: waitForVersion storageserver.actor.cpp:631."""
        from ..flow.error import FdbError

        if version > self.version.get() + g_knobs.server.max_versions_in_flight:
            raise FdbError("future_version")
        if version < self.durable_version:
            # The window below the durable floor is gone (ref: reads below
            # oldestVersion -> transaction_too_old, storageserver :640).
            raise FdbError("transaction_too_old")
        await self.version.when_at_least(version)
        if version < self.durable_version:  # floor may have risen across the wait
            raise FdbError("transaction_too_old")

    async def _serve_get_value(self):
        while True:
            req, reply = await self._gv_stream.pop()
            self.process.spawn(self._get_value_one(req, reply), "ss_gv")

    async def _get_value_one(self, req: GetValueRequest, reply):
        try:
            await self._wait_for_version(req.version)
        except Exception as e:  # noqa: BLE001
            reply.send_error(getattr(e, "name", "internal_error"))
            return
        reply.send(
            GetValueReply(
                value=self._get_current(req.key, req.version), version=req.version
            )
        )

    async def _serve_get_key_values(self):
        while True:
            req, reply = await self._gkv_stream.pop()
            self.process.spawn(self._get_key_values_one(req, reply), "ss_gkv")

    async def _get_key_values_one(self, req: GetKeyValuesRequest, reply):
        try:
            await self._wait_for_version(req.version)
        except Exception as e:  # noqa: BLE001
            reply.send_error(getattr(e, "name", "internal_error"))
            return
        data = self._range_at(
            req.begin, req.end, req.version, req.limit + 1, req.reverse
        )
        more = len(data) > req.limit
        reply.send(
            GetKeyValuesReply(data=data[: req.limit], more=more, version=req.version)
        )

    def _range_at(self, begin, end, version, limit, reverse):
        """Window-over-base merged range read (window clears mask base keys).

        Two-pointer merge over the already-sorted base and window key lists
        with early exit, so a limited read costs O(limit + skipped-masked),
        not O(range size).
        """
        if self.kvstore is None:
            return self.store.get_range(begin, end, version, limit, reverse)
        base_keys = self.kvstore._keys
        bi = bisect_left(base_keys, begin)
        bj = bisect_left(base_keys, end)
        wkeys = self.store.sorted_keys
        wi = bisect_left(wkeys, begin)
        wj = bisect_left(wkeys, end)
        rows: list = []
        before = (lambda x, y: x > y) if reverse else (lambda x, y: x < y)
        # Index the sorted lists in place (no range-sized copies) so a
        # limited read really is O(limit + masked keys skipped).
        if reverse:
            ia, ea, step = bj - 1, bi - 1, -1
            ib, eb = wj - 1, wi - 1
        else:
            ia, ea, step = bi, bj, 1
            ib, eb = wi, wj
        while (ia != ea or ib != eb) and len(rows) < limit:
            ka = base_keys[ia] if ia != ea else None
            kb = wkeys[ib] if ib != eb else None
            if kb is None or (ka is not None and before(ka, kb)):
                k = ka
                ia += step
            elif ka is None or before(kb, ka):
                k = kb
                ib += step
            else:  # same key in both
                k = ka
                ia += step
                ib += step
            touched, wv = self.store.get_stamped(k, version)
            v = wv if touched else self.kvstore.read_value(k)
            if v is not None:
                rows.append((k, v))
        return rows

    async def _serve_get_version(self):
        while True:
            _req, reply = await self._ver_stream.pop()
            reply.send(self.version.get())
