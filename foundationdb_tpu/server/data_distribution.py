"""DataDistribution v1: shard placement driven by transactions on the
`\xff` system keyspace.

Ref: fdbserver/DataDistribution.actor.cpp:493 (DDTeamCollection),
fdbserver/MoveKeys.actor.cpp (startMoveKeys/finishMoveKeys updating the
keyServers map transactionally), fdbserver/DataDistributionTracker.actor.cpp
(shard split).  Like the reference, DD is a CLIENT of the database it
manages: every placement change is an ordinary transaction on system keys,
so handoffs serialize with user commits at exact versions and survive
recoveries via the log.

v1 scope: seeding, explicit split/move, even spreading, and shard-state
polling.  Failure-driven re-replication needs storage replication >= 2 (a
dead source with replication 1 has nothing to fetch from) and lands with
the tag-partitioned log system.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..flow.error import FdbError
from . import system_keys as sk
from .interfaces import GetShardStateRequest, StorageInterface
from .storage import KEYSPACE_END


class DataDistributor:
    """Runs MoveKeys-style protocols through a client Database handle."""

    def __init__(self, db, storages: Dict[str, StorageInterface] = None):
        self.db = db
        self.loop = db.process.network.loop
        # Known storages (also discoverable from \xff/serverList/).
        self.storages: Dict[str, StorageInterface] = dict(storages or {})

    # --- bootstrap ---
    async def register_storages(self, storages: Dict[str, StorageInterface]):
        """Publish \xff/serverList/ entries so every role can resolve ids to
        interfaces from the mutation stream (ref: serverListKeyFor)."""
        self.storages.update(storages)

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            for sid, iface in storages.items():
                tr.set(sk.server_list_key(sid), sk.encode_server_entry(iface))

        await self.db.run(txn)

    async def seed(self, team: List[str]):
        """Record initial ownership of the whole keyspace by `team` (which
        must already hold the data — at bootstrap the first storage owns
        everything).  No-op if a shard map already exists (ref: the seeding
        in the master's RECOVERY_TRANSACTION for new databases)."""
        existing = await self.read_shard_map()
        if existing:
            return

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            tr.set(
                sk.key_servers_key(b""),
                sk.encode_key_servers(team, [], KEYSPACE_END),
            )

        await self.db.run(txn)

    # --- introspection ---
    async def read_shard_map(self) -> List[Tuple[bytes, bytes, list, list]]:
        """[(begin, end, team, dest_or_empty)] from the authoritative
        keyspace (ref: krmGetRanges over keyServers)."""

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            return await tr.get_range(sk.KEY_SERVERS_PREFIX, sk.KEY_SERVERS_END)

        rows = await self.db.run(txn)
        out = []
        for k, v in rows:
            src, dest, end = sk.decode_key_servers(v)
            out.append((sk.key_servers_begin(k), end, src, dest))
        return out

    # --- operations ---
    async def split(self, at_key: bytes):
        """Split the shard containing at_key into two (metadata only; no
        data movement — both halves stay on the same team).  Ref:
        shardSplitter DataDistributionTracker.actor.cpp.

        The containing record is READ INSIDE the transaction (ref:
        startMoveKeys reading keyServers in-txn, MoveKeys.actor.cpp): a
        concurrent move/merge/split conflicts and retries this txn against
        the fresh map instead of being silently overwritten."""

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            # Only the CONTAINING record (greatest begin <= at_key) joins
            # the read set: a full-map scan would conflict this split with
            # every unrelated DD metadata write and rescan O(map) per retry.
            rows = await tr.get_range(
                sk.KEY_SERVERS_PREFIX,
                sk.key_servers_key(at_key) + b"\x00",
                limit=1,
                reverse=True,
            )
            for k, v in rows:
                b = sk.key_servers_begin(k)
                team, dest, e = sk.decode_key_servers(v)
                if b < at_key and (e is None or at_key < e):
                    assert not dest, "split during a move is not supported (v1)"
                    tr.set(
                        sk.key_servers_key(b),
                        sk.encode_key_servers(team, [], at_key),
                    )
                    tr.set(
                        sk.key_servers_key(at_key),
                        sk.encode_key_servers(team, [], e),
                    )
            # at_key already a boundary (or outside the map): nothing to do.

        await self.db.run(txn)

    async def move(self, begin: bytes, dest_team: List[str],
                   poll_interval: float = 0.05, max_polls: int = 2000):
        """Move the shard beginning at `begin` to `dest_team`: startMove
        record -> wait for every destination to report FETCHED -> settle
        (ref: startMoveKeys / waitForShardReady / finishMoveKeys,
        MoveKeys.actor.cpp).

        Both metadata transactions READ the record in-txn before writing,
        so a split/merge/other-move committing between this actor's steps
        conflicts (and retries against fresh state) or raises ValueError
        (shard gone / move superseded) instead of resurrecting a stale
        end-key into the map — the exact overwrite hazard the reference
        avoids the same way (MoveKeys.actor.cpp startMoveKeys reads
        keyServers inside the transaction)."""

        async def start(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            raw = await tr.get(sk.key_servers_key(begin))
            if raw is None:
                raise ValueError(f"no shard begins at {begin!r}")
            team, dest, e = sk.decode_key_servers(raw)
            if dest and set(dest) == set(dest_team):
                return ("drive", e)  # same move in flight; re-drive to done
            if not dest and set(team) == set(dest_team):
                return ("done", e)
            # Fresh move, or superseding an in-flight move whose destination
            # changed (e.g. heal() retargeting after a dest died): rewrite
            # the start record; destinations cancel stale AddingShards.
            tr.set(
                sk.key_servers_key(begin),
                sk.encode_key_servers(team, dest_team, e),
            )
            return ("drive", e)

        state, e = await self.db.run(start)
        if state == "done":
            return

        await self._wait_fetched(begin, e, dest_team, poll_interval, max_polls)

        async def finish(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            raw = await tr.get(sk.key_servers_key(begin))
            if raw is None:
                raise ValueError(f"shard {begin!r} vanished mid-move")
            _team, dest, e2 = sk.decode_key_servers(raw)
            if set(dest) != set(dest_team):
                raise ValueError(f"move of {begin!r} superseded")
            tr.set(
                sk.key_servers_key(begin),
                sk.encode_key_servers(dest_team, [], e2),
            )

        await self.db.run(finish)

    async def _wait_fetched(self, begin: bytes, end: bytes, dest_team: List[str],
                            poll_interval: float, max_polls: int):
        req = GetShardStateRequest(begin=begin, end=end)
        for _ in range(max_polls):
            states = []
            for sid in dest_team:
                iface = self.storages.get(sid)
                if iface is None:
                    states.append("unknown")
                    continue
                try:
                    states.append(
                        await iface.get_shard_state.get_reply(
                            self.db.process, req
                        )
                    )
                except FdbError:
                    states.append("unreachable")
            if all(s in ("fetched", "readable") for s in states):
                return
            if "missing" in states:
                # The destination lost the in-flight move (crash): restart
                # it by rewriting the startMove record — AND the serverList
                # entries, because a destination that rejoined fresh at the
                # current version never saw the original serverList writes
                # and cannot resolve its fetch sources without them (ref:
                # the serverListKeys rows re-read by fetchKeys).  Read
                # in-txn: a superseding move between poll and rewrite must
                # not be clobbered with this attempt's stale record.
                async def restart(tr):
                    tr.options["access_system_keys"] = True
                    tr.options["lock_aware"] = True
                    raw = await tr.get(sk.key_servers_key(begin))
                    if raw is None:
                        return
                    team, dest, e2 = sk.decode_key_servers(raw)
                    if not dest:
                        return
                    for sid in set(team) | set(dest):
                        iface = self.storages.get(sid)
                        if iface is not None:
                            tr.set(
                                sk.server_list_key(sid),
                                sk.encode_server_entry(iface),
                            )
                    tr.set(
                        sk.key_servers_key(begin),
                        sk.encode_key_servers(team, dest, e2),
                    )

                await self.db.run(restart)
            await self.loop.delay(poll_interval)
        raise TimeoutError(f"shard [{begin!r}, {end!r}) never became fetched")

    async def spread_evenly(self, split_points: Optional[List[bytes]] = None,
                            replication: int = 1):
        """Partition the USER keyspace across all registered storages: split
        at fixed byte boundaries (or given points) and round-robin TEAMS of
        `replication` consecutive storages (ref: DDTeamCollection building
        storage teams per policy, DataDistribution.actor.cpp:493).  The
        system keyspace (\xff...) stays on its current owner.  The dynamic,
        byte-sample-driven rebalancer replaces this once storage metrics
        exist (ref: DataDistributionTracker byte samples)."""
        ids = sorted(self.storages)
        if len(ids) < 2:
            return
        replication = min(replication, len(ids))
        if split_points is None:
            n = len(ids)
            split_points = [bytes([256 * i // n]) for i in range(1, n)]
        for p in split_points:
            await self.split(p)
        await self.split(b"\xff")  # keep the system keyspace its own shard
        shards = [
            (b, e, team) for b, e, team, dest in await self.read_shard_map()
            if not dest and b < b"\xff"
        ]
        for i, (b, _e, team) in enumerate(shards):
            target = [ids[(i + r) % len(ids)] for r in range(replication)]
            if set(team) != set(target):
                await self.move(b, target)

    async def process_exclusions(
        self, replacement_id: Optional[str] = None, tlogs: list = None
    ) -> list:
        """Apply operator exclusions (ref: DD reacting to
        excludedServersKeys — excluded servers are treated like failed
        ones): move every excluded server's shards to its teammates (or the
        replacement), and when `tlogs` interfaces are given, unregister the
        excluded server's log tag so its PERSISTED pop floor stops holding
        the logs' discard floor.  Returns the ids acted on."""
        from ..client.management import get_excluded_servers
        from .interfaces import TLogPopRequest

        excluded = await get_excluded_servers(self.db)
        acted = []
        # One authoritative map read serves every membership check; heal()
        # re-reads for itself, so refresh only after an actual heal.
        shard_map = await self.read_shard_map()
        for sid in excluded:
            in_map = any(
                sid in set(dest or team)
                for _b, _e, team, dest in shard_map
            )
            if not in_map:
                continue
            await self.heal(sid, replacement_id)
            shard_map = await self.read_shard_map()
            for tl in tlogs or []:
                await tl.pop.get_reply(
                    self.db.process,
                    TLogPopRequest(tag=sid, unregister=True),
                )
            acted.append(sid)
        return acted

    async def _team_metrics(self, begin, end, team):
        """One team member's byte-sample metrics for a range, or None when
        no member is reachable (shared by the split and merge trackers)."""
        from .interfaces import GetStorageMetricsRequest

        members = [sid for sid in team if sid in self.storages]
        if not members:
            return None
        try:
            return await self.storages[members[0]].get_storage_metrics.get_reply(
                self.db.process,
                GetStorageMetricsRequest(
                    begin=begin, end=end if end is not None else b""
                ),
            )
        except FdbError:
            return None

    async def auto_split(self, max_shard_bytes: int) -> list:
        """One split round driven by the storages' byte samples (ref:
        DataDistributionTracker shard-size tracking + splitting,
        DataDistributionTracker.actor.cpp): every shard whose sampled bytes
        exceed the threshold splits at the key holding ~half its weight.
        Returns the split keys applied."""
        applied = []
        for b, e, team, dest in await self.read_shard_map():
            if dest:
                continue  # mid-move; split() cannot rewrite a move record
            m = await self._team_metrics(b, e, team)
            if m is None:
                continue
            if m.bytes <= max_shard_bytes or m.split_key is None:
                continue
            if m.split_key <= b or (e is not None and m.split_key >= e):
                continue
            await self.split(m.split_key)
            applied.append(m.split_key)
        return applied

    async def auto_merge(self, min_shard_bytes: int) -> list:
        """One merge round: ADJACENT shards owned by the SAME settled team
        whose combined sampled bytes stay under the threshold coalesce into
        one keyServers record (ref: shard merging when sizes fall below
        SHARD_MIN_BYTES_PER_KSECOND territory —
        DataDistributionTracker.actor.cpp's brokenPromiseToNever merge
        path).  Never merges across the system-keyspace boundary or into
        in-flight moves.  Returns the begin keys of absorbed shards."""
        async def sampled(b, e, team):
            m = await self._team_metrics(b, e, team)
            return None if m is None else m.bytes

        absorbed = []
        shard_map = await self.read_shard_map()
        i = 0
        carry = None  # (index, bytes): the previous right shard's sample
        while i + 1 < len(shard_map):
            b1, e1, t1, d1 = shard_map[i]
            b2, e2, t2, d2 = shard_map[i + 1]
            if (
                d1
                or d2
                or e1 != b2
                or set(t1) != set(t2)
                or b2 >= b"\xff"  # never absorb across/into system space
                # end=None means "through the end of the keyspace" — past
                # the system boundary by definition.
                or ((e2 is None or e2 > b"\xff") and b1 < b"\xff")
            ):
                i += 1
                continue
            # Each shard is measured once per round: the right-hand sample
            # carries forward as the next iteration's left-hand one.
            if carry is not None and carry[0] == i:
                s1 = carry[1]
            else:
                s1 = await sampled(b1, e1, t1)
            s2 = await sampled(b2, e2, t2)
            carry = (i + 1, s2)
            if s1 is None or s2 is None or s1 + s2 > min_shard_bytes:
                i += 1
                continue

            async def merge_txn(tr, b1=b1, b2=b2):
                tr.options["access_system_keys"] = True
                tr.options["lock_aware"] = True
                # Re-validate in-txn (a concurrent move/split between the
                # sampling reads and this commit must abort the merge, not
                # be overwritten).
                raw1 = await tr.get(sk.key_servers_key(b1))
                raw2 = await tr.get(sk.key_servers_key(b2))
                if raw1 is None or raw2 is None:
                    return False
                t1x, d1x, e1x = sk.decode_key_servers(raw1)
                t2x, d2x, e2x = sk.decode_key_servers(raw2)
                if d1x or d2x or e1x != b2 or set(t1x) != set(t2x):
                    return False
                # One record covers the union; the boundary record clears.
                tr.set(
                    sk.key_servers_key(b1),
                    sk.encode_key_servers(list(t1x), [], e2x),
                )
                tr.clear(sk.key_servers_key(b2))
                return True

            if not await self.db.run(merge_txn):
                i += 1
                carry = None
                continue
            absorbed.append(b2)
            # The merged shard may merge again with its next neighbor.
            shard_map = await self.read_shard_map()
            carry = None  # indexes changed; stale samples must not carry
        return absorbed

    async def heal(self, dead_id: str, replacement_id: Optional[str] = None):
        """Re-replicate every shard that lists a dead storage: survivors
        stay the fetch sources, a replacement (or nothing, dropping to a
        smaller team) joins (ref: teamTracker reacting to failures,
        DataDistribution.actor.cpp:1237).  Requires replication >= 2 for
        shards whose only copy died."""
        for b, _e, team, dest in await self.read_shard_map():
            members = set(dest or team)
            if dead_id not in members:
                continue
            survivors = [s for s in (dest or team) if s != dead_id]
            if not survivors:
                raise RuntimeError(
                    f"shard at {b!r}: sole replica {dead_id} died; data lost"
                )
            new_team = list(survivors)
            if replacement_id and replacement_id not in new_team:
                new_team.append(replacement_id)
            await self.move(b, new_team)
