"""Sequencer: the master's commit-version allocator.

Ref: masterserver.actor.cpp getVersion :783 — hands out monotone commit
versions with prevVersion chaining so resolvers and logs can totally order
batches; provideVersions :850 serves the stream.  Version arithmetic follows
the reference: advance roughly versions_per_second * elapsed, never
backwards.
"""

from __future__ import annotations

from ..flow.asyncvar import NotifiedVersion
from ..flow.knobs import g_knobs
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from .interfaces import (
    GetCommitVersionReply,
    SequencerInterface,
)


class Sequencer:
    def __init__(
        self, process: SimProcess, epoch_begin_version: int = 0, epoch: int = 0
    ):
        self.process = process
        self.epoch = epoch
        self.version = epoch_begin_version  # last version handed out
        self.committed = NotifiedVersion(epoch_begin_version)
        self._last_grant_time = process.network.loop.now()
        self._commit_stream = RequestStream(process, "get_commit_version", well_known=True)
        self._report_stream = RequestStream(process, "report_committed", well_known=True)
        self._read_stream = RequestStream(process, "get_committed_version", well_known=True)
        process.spawn_observed(self._serve_commit_versions(), "sequencer_commit")
        process.spawn_observed(self._serve_reports(), "sequencer_report")
        process.spawn_observed(self._serve_reads(), "sequencer_read")

    def interface(self) -> SequencerInterface:
        return SequencerInterface(
            get_commit_version=self._commit_stream.ref(),
            report_committed=self._report_stream.ref(),
            get_committed_version=self._read_stream.ref(),
        )

    def _next_version(self) -> tuple:
        """(version, prev_version): versions track virtual time (ref:
        getVersion computes t1*VERSIONS_PER_SECOND skew :800-809)."""
        from ..flow.buggify import buggify

        loop = self.process.network.loop
        now = loop.now()
        vps = g_knobs.server.versions_per_second
        advance = max(1, int((now - self._last_grant_time) * vps))
        if buggify("sequencer_version_jump"):
            # BUGGIFY: a large version gap (clock skew analog) — exercises
            # MVCC window GC and too-old classification downstream.
            advance += int(loop.rng.random01() * vps * 0.5)
        self._last_grant_time = now
        prev = self.version
        self.version = prev + advance
        return self.version, prev

    async def _serve_commit_versions(self):
        while True:
            req_epoch, reply = await self._commit_stream.pop()
            # Epoch fencing: a previous generation's proxy can still reach
            # this stream (well-known token on a rebooted machine) — serving
            # it would consume a (prev, version) pair whose batch the
            # resolvers reject by THEIR epoch check, leaving a permanent
            # hole in the prevVersion chain that wedges every later batch.
            # The reference's master only serves proxies of its own
            # registration (getVersion, masterserver.actor.cpp:783).
            if req_epoch is not None and req_epoch != self.epoch:
                reply.send_error("operation_failed")
                continue
            version, prev = self._next_version()
            reply.send(GetCommitVersionReply(version=version, prev_version=prev))

    async def _serve_reports(self):
        while True:
            version, reply = await self._report_stream.pop()
            if version > self.committed.get():
                self.committed.set(version)
            reply.send(None)

    async def _serve_reads(self):
        while True:
            _req, reply = await self._read_stream.pop()
            reply.send(self.committed.get())
