"""Resolution balancing: move resolver split points toward the load.

Ref: the master's resolution balancer — it polls every resolver's
ResolutionMetricsRequest, and when the load skews it asks the overloaded
resolver for a split key from its iopsSample (ResolutionSplitRequest,
ResolverInterface.h:108-131; Resolver.actor.cpp:276-284) and moves the
boundary.  Here the new partition is committed as a system-key transaction
(`\xff/conf/resolverSplit`), so every proxy applies it at an exact version
through the state-transaction channel and runs the both-owners overlap
window (proxy.py `_old_bounds`) before retiring the old partition.
"""

from __future__ import annotations

from typing import List, Optional

from ..flow.knobs import g_knobs
from .interfaces import ResolutionSplitRequest, ResolverInterface
from . import system_keys as sk


class ResolverBalancer:
    def __init__(
        self,
        db,
        resolvers: List[ResolverInterface],
        split_keys: List[bytes],
        min_ops: int = 50,
        ratio: float = 1.5,
    ):
        assert len(split_keys) == len(resolvers) - 1
        self.db = db
        self.resolvers = resolvers
        self.split_keys = list(split_keys)
        self.min_ops = min_ops
        self.ratio = ratio
        self.moves = 0

    async def run_once(self) -> Optional[List[bytes]]:
        """One balancing round; returns the new split list if a boundary
        moved, else None.

        The whole round is a read-modify-write of the partition spanning
        several awaits (metrics polls, the split RPC, the commit), so the
        plan is computed from one snapshot (`base`), the commit validates
        the durable partition against it with a conflict-checked read
        (a concurrent mover aborts exactly like any MVCC write-write
        conflict), and the in-memory view is only adopted if no one else
        repartitioned while we were suspended — a stale plan is dropped,
        never stomped over a newer one."""
        proc = self.db.process
        base = self.split_keys  # the snapshot this round's plan is built on
        ops = []
        for r in self.resolvers:
            rep = await r.metrics.get_reply(proc, None)
            ops.append(rep.ops)
        # The most imbalanced ADJACENT pair among those that PASS the
        # move gate (boundaries only move between neighbors, like the
        # reference's balancer).  Gating after selection would let one big
        # but-below-ratio gap starve a qualifying pair elsewhere forever.
        best, best_gap = None, 0
        for i in range(len(ops) - 1):
            oi, oj = ops[i], ops[i + 1]
            if max(oi, oj) < self.min_ops or max(oi, oj) <= self.ratio * max(
                1, min(oi, oj)
            ):
                continue
            gap = abs(oi - oj)
            if gap > best_gap:
                best, best_gap = i, gap
        if best is None:
            return None
        i = best
        oi, oj = ops[i], ops[i + 1]
        bounds = sk.bounds_from_split_keys(base)
        target = (oi + oj) / 2.0
        if oi > oj:
            # Donor on the left: keep its first `target/oi` of mass; the
            # boundary moves LEFT to the donated remainder's first key.
            lo, hi = bounds[i]
            new_key = await self.resolvers[i].split.get_reply(
                proc,
                ResolutionSplitRequest(
                    begin=lo, end=hi, fraction=target / max(oi, 1)
                ),
            )
        else:
            # Donor on the right: give away its first (oj-target)/oj of
            # mass; the boundary moves RIGHT to the key after the donation.
            lo, hi = bounds[i + 1]
            new_key = await self.resolvers[i + 1].split.get_reply(
                proc,
                ResolutionSplitRequest(
                    begin=lo,
                    end=hi,
                    fraction=(oj - target) / max(oj, 1),
                ),
            )
        if new_key is None or new_key in (b"",):
            return None
        old = base[i]  # fdblint: ignore[WAIT001]: deliberate snapshot — the commit txn re-validates the durable partition against base and drops a stale plan (see docstring)
        if new_key == old:
            return None
        new_splits = list(base)
        new_splits[i] = new_key
        if sorted(set(new_splits)) != new_splits or b"" in new_splits:
            return None  # refuse a degenerate partition

        stale = []

        async def txn(tr):
            tr.options["access_system_keys"] = True
            # Conflict-checked read: if another mover committed while this
            # round was suspended, either we see its value here and abort
            # the plan, or the resolver aborts one of the two commits —
            # the durable partition is never built from a stale snapshot.
            cur = await tr.get(sk.RESOLVER_SPLIT_KEY)
            if cur is not None and sk.decode_resolver_split(cur) != list(base):
                stale.append(True)
                return
            tr.set(sk.RESOLVER_SPLIT_KEY, sk.encode_resolver_split(new_splits))

        await self.db.run(txn)
        if stale or self.split_keys is not base:
            return None  # someone repartitioned during our awaits
        self.split_keys = new_splits
        self.moves += 1
        return new_splits

    async def run(self, interval: float = 0.5, rounds: Optional[int] = None):
        """Poll loop.  After a move, wait out the proxies' overlap window
        (MVCC window + in-flight depth, in seconds) before moving again —
        overlapping transitions would stack overlays."""
        loop = self.db.process.network.loop
        vps = g_knobs.server.versions_per_second
        overlap_s = (
            g_knobs.server.max_write_transaction_life_versions
            + g_knobs.server.max_versions_in_flight
        ) / vps
        n = 0
        while rounds is None or n < rounds:
            n += 1
            moved = await self.run_once()
            await loop.delay(interval + (overlap_s if moved else 0.0))
            if moved:
                # Discard the overlap window's metrics: both owners counted
                # the donated range's traffic while proxies unioned old+new
                # bounds, so the counters read double until reset.
                for r in self.resolvers:
                    try:
                        await r.metrics.get_reply(self.db.process, None)
                    except Exception:  # noqa: BLE001 - resolver died:  # fdblint: ignore[ERR001]: best-effort counter reset on a dying generation — recovery replaces the role anyway
                        pass  # the generation is ending anyway


class ShardBalancer:
    """Self-balancing shard mesh (ISSUE 18): the in-process twin of the
    RPC balancer above, moving the MESH-SHARDED conflict set's split
    points from live signals — per-shard mirror occupancy gauges, the
    PR-12 decayed contended-range sample (via ``load_fn``), and the
    admission-pressure scalar for 2→4→8 shard-count scaling.  This is
    the reference's dataDistribution/shard-mover role, scoped to the
    resolver's key partition.

    Every call to :meth:`evaluate` appends one decision record to
    ``decisions`` — a replayable transition log built only from
    deterministic inputs (occupancy counts, supplied loads/pressure,
    the tick counter), so same-seed runs dump byte-identical logs.
    Two anti-flap gates: ``hysteresis`` consecutive over-``ratio``
    evaluations must agree before a move, and every committed move
    starts a ``cooldown`` of idle ticks (the reference balancer's
    overlap-window wait, in ticks instead of versions)."""

    def __init__(
        self,
        conflict_set,
        ratio: float = 2.0,
        hysteresis: int = 2,
        cooldown: int = 4,
        min_boundaries: int = 32,
        scale_up_pressure: float = 0.85,
        load_fn=None,
    ):
        self.conflict_set = conflict_set
        self.ratio = ratio
        self.hysteresis = hysteresis
        self.cooldown = cooldown
        self.min_boundaries = min_boundaries
        self.scale_up_pressure = scale_up_pressure
        self.load_fn = load_fn
        self.decisions: List[dict] = []
        self.moves = 0
        self._ticks = 0
        self._streak = 0
        self._cooldown_left = 0

    def decisions_json(self) -> str:
        """Canonical dump of the decision log — the same-seed
        byte-identity artifact (cli shards / soak resharding section)."""
        import json

        return json.dumps(
            self.decisions, sort_keys=True, separators=(",", ":")
        )

    def evaluate(self, pressure: Optional[float] = None) -> dict:
        """One balancing tick; returns (and logs) the decision.

        ``pressure`` is the admission-pressure scalar in [0, 1] (e.g.
        released/limit from the ratekeeper, or a queue-depth fraction):
        sustained pressure at/above ``scale_up_pressure`` doubles the
        shard count (bounded by the set's ``max_shards``) instead of
        just moving boundaries.  Synchronous — no await — so it can
        never interleave with a batch mid-resolve."""
        cs = self.conflict_set
        self._ticks += 1
        occ = cs.shard_occupancy()
        n = cs.n_shards
        entry: dict = {
            "tick": self._ticks,
            "shards": n,
            "occupancy": [int(o) for o in occ],
            "action": "idle",
        }
        if pressure is not None:
            entry["pressure"] = round(float(pressure), 4)
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            entry["action"] = "cooldown"
            self.decisions.append(entry)
            return entry
        if getattr(cs, "_pinned", False):
            # Long-key pin: the mirrors hold keys the device cannot
            # encode, so new split points may not either — sit out.
            entry["action"] = "pinned"
            self._streak = 0
            self.decisions.append(entry)
            return entry
        total = sum(occ)
        mean = total / max(1, n)
        imb = (max(occ) / mean) if mean > 0 else 0.0
        loads = None
        if self.load_fn is not None:
            loads = [int(x) for x in self.load_fn()]
            if len(loads) == n and sum(loads) > 0:
                entry["load"] = loads
                lmean = sum(loads) / n
                imb = max(imb, max(loads) / lmean)
            else:
                loads = None
        entry["imbalance"] = round(imb, 3)
        want_scale = (
            pressure is not None
            and pressure >= self.scale_up_pressure
            and n < getattr(cs, "max_shards", n)
        )
        if imb >= self.ratio or want_scale:
            self._streak += 1
        else:
            self._streak = 0
        entry["streak"] = self._streak
        if self._streak < self.hysteresis or total < self.min_boundaries:
            self.decisions.append(entry)
            return entry
        target_n = min(getattr(cs, "max_shards", n), n * 2) if want_scale else n
        new_split = cs.balance_split_keys(target_n)
        if [bytes(k) for k in new_split] == list(cs.split_keys):
            entry["action"] = "no_candidate"
            self._streak = 0
            self.decisions.append(entry)
            return entry
        try:
            move = cs.reshard(
                new_split, reason=f"balancer_tick{self._ticks}"
            )
        except ValueError as e:
            # The set refused the partition (e.g. a candidate key the
            # device cannot encode): log and stand down — never let a
            # rejected plan kill the balancer actor.
            entry["action"] = "rejected"
            entry["error"] = str(e)
            self._streak = 0
            self.decisions.append(entry)
            return entry
        self._streak = 0
        self._cooldown_left = self.cooldown
        entry["action"] = "scale" if target_n != n else "move"
        entry["move"] = {"seq": move["seq"], "action": move["action"]}
        if move["action"] != "deferred":
            self.moves += 1
        self.decisions.append(entry)
        return entry
