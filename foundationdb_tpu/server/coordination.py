"""Coordinators: replicated generation register + leader election.

Ref: fdbserver/Coordination.actor.cpp — localGenerationReg :125 (per-key
(value, readGen, writeGen) with generation promises), leaderRegister :203
(candidacy/nominee/heartbeat), CoordinatedState.actor.cpp (quorum
read/write with coordinated_state_conflict), LeaderElection.actor.cpp
(tryBecomeLeader), fdbclient/MonitorLeader.actor.cpp (majority-nominee
polling).

The rebuild keeps the protocol essence on the deterministic fabric:

  - generation register: read(key, gen) promises not to accept older
    writes; write(key, value, gen) succeeds iff gen >= every promised gen
  - quorum client: read from a majority, take the value with the highest
    write generation; write to a majority at a higher generation or fail
    with coordinated_state_conflict
  - leader register: leases; nominee = lowest (priority, change_id) among
    live candidates; candidates poll and hold a majority to lead

All timing is virtual; elections are seed-reproducible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..flow.asyncvar import AsyncVar
from ..flow.error import FdbError
from ..flow.eventloop import all_of, first_of, timeout_after
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef

CANDIDATE_TTL = 2.0
NOMINEE_TICK = 0.5
POLL_INTERVAL = 0.5


@dataclass(frozen=True, order=True)
class LeaderInfo:
    """Candidate identity; lower sorts first and wins nomination (ref:
    LeaderInfo operator< — priority then changeID)."""

    priority: int
    change_id: int
    address: str = field(compare=False, default="")
    payload: object = field(compare=False, default=None)


# A generation is (counter, salt): totally ordered, unique per session
# (ref: UniqueGeneration(generation, uid), CoordinationInterface.h).
ZERO_GEN = (0, 0)

# Registry key persisting a retired coordinator's forward pointer.
FORWARD_KEY = b"\xff/forward"


@dataclass
class GenReadRequest:
    key: bytes
    gen: tuple  # the reader's unique generation (plants the read promise)


@dataclass
class GenReadReply:
    value: Optional[bytes]
    write_gen: tuple
    read_gen: tuple


@dataclass
class GenWriteRequest:
    key: bytes
    value: bytes
    gen: tuple


@dataclass
class CandidacyRequest:
    key: bytes
    info: LeaderInfo
    known_nominee: Optional[int]  # change_id the candidate last saw


@dataclass
class CoordinatorInterface:
    gen_read: RequestStreamRef = None
    gen_write: RequestStreamRef = None
    candidacy: RequestStreamRef = None
    get_leader: RequestStreamRef = None
    set_forward: RequestStreamRef = None


def coordinator_interface_at(address: str) -> CoordinatorInterface:
    """Interface for the coordinator at `address` from its well-known
    tokens alone — how a process reaches coordinators it only knows from a
    cluster-file line (ref: the WLTOKEN_* constants,
    CoordinationInterface.h)."""
    from ..rpc.stream import well_known_token
    from ..rpc.network import Endpoint

    def ref(name: str) -> RequestStreamRef:
        return RequestStreamRef(Endpoint(address, well_known_token(name)), name)

    return CoordinatorInterface(
        gen_read=ref("coord_gen_read"),
        gen_write=ref("coord_gen_write"),
        candidacy=ref("coord_candidacy"),
        get_leader=ref("coord_get_leader"),
        set_forward=ref("coord_set_forward"),
    )


class CoordinatorSet:
    """The mutable "cluster file": the coordinator addresses a process
    currently believes in.  Election/monitor actors re-read it every round,
    so a quorum change retargets them without restarts (ref: the connection
    file rewrite in MonitorLeader.actor.cpp when coordinators forward)."""

    def __init__(self, addresses: List[str],
                 interfaces: Optional[List[CoordinatorInterface]] = None):
        self.addresses = list(addresses)
        self.interfaces = (
            list(interfaces)
            if interfaces is not None
            else [coordinator_interface_at(a) for a in addresses]
        )
        self.changes = 0

    def retarget(self, addresses: List[str]):
        if list(addresses) == self.addresses:
            return
        self.addresses = list(addresses)
        self.interfaces = [coordinator_interface_at(a) for a in addresses]
        self.changes += 1


def _resolve_coords(coordinators) -> List[CoordinatorInterface]:
    """Accept a plain interface list (legacy call sites) or a
    CoordinatorSet (retargetable)."""
    if isinstance(coordinators, CoordinatorSet):
        return coordinators.interfaces
    return coordinators


# A forwarded coordinator nominates this pseudo-leader: priority makes it
# win min() immediately, the shared change_id makes the majority count
# converge, and the payload carries the new addresses (ref: ForwardRequest,
# Coordination.actor.cpp — "the cluster key is now served elsewhere").
FORWARD_PRIORITY = -(1 << 40)


def _forward_info(addrs: List[str]) -> LeaderInfo:
    import zlib

    blob = b",".join(a.encode() for a in addrs)
    return LeaderInfo(
        priority=FORWARD_PRIORITY,
        change_id=zlib.crc32(blob),
        payload={"moved_to": list(addrs)},
    )


class Coordinator:
    """One coordinator process: generation register + leader register.

    With `fs`, the generation register (values AND promises) is persisted
    through the durable storage stack before any reply (ref: localGenerationReg
    commits its OnDemandStore before answering, Coordination.actor.cpp:125-160)
    — a restarted coordinator keeps its promises, so a stale CoordinatedState
    write can never reach quorum after a whole-cluster power loss.  The leader
    register stays ephemeral (leases, as in the reference)."""

    def __init__(
        self,
        process: SimProcess,
        fs=None,
        filename: str = "coordination.dq",
    ):
        self.process = process
        self.fs = fs
        self.filename = filename
        self._store = None
        # key -> (value, read_gen, write_gen)
        self.registry: Dict[bytes, Tuple[Optional[bytes], int, int]] = {}
        # leader register (single implicit key, like one leaderRegister actor)
        self.candidates: Dict[int, Tuple[LeaderInfo, float]] = {}
        self.nominee: Optional[LeaderInfo] = None
        self._waiters: List = []  # (known_change_id, reply)
        # Non-None after a quorum move: addresses this coordinator forwards
        # every election client to (ref: ForwardRequest handling).
        self.forward: Optional[List[str]] = None
        self._gr = RequestStream(process, "coord_gen_read", well_known=True)
        self._gw = RequestStream(process, "coord_gen_write", well_known=True)
        self._cd = RequestStream(process, "coord_candidacy", well_known=True)
        self._gl = RequestStream(process, "coord_get_leader", well_known=True)
        self._fw = RequestStream(process, "coord_set_forward", well_known=True)
        process.spawn_observed(self._boot(), "coord_boot")

    async def _boot(self):
        """Recover the generation register from disk, then serve.  Requests
        arriving before recovery park in the streams' queues."""
        if self.fs is not None:
            from ..fileio.kvstore import KeyValueStoreMemory
            from ..rpc.wire import decode_frame

            self._store = await KeyValueStoreMemory.open(
                self.fs, self.process, self.filename
            )
            for k, v in self._store.read_range(b"", b"\xff" * 16):
                self.registry[k] = decode_frame(v)
            fwd = self.registry.get(FORWARD_KEY)
            if getattr(self, "_forward_cleared", False):
                # clear_forward ran while this boot was still loading: the
                # clear wins over whatever the disk said.
                self.forward = None
                self.registry[FORWARD_KEY] = (b"", ZERO_GEN, ZERO_GEN)
                await self._persist(FORWARD_KEY)
            elif fwd is not None and fwd[0]:
                # A rebooted retired coordinator must keep forwarding, or a
                # client with a stale cluster file could re-elect on the
                # old quorum (ref: forward is durable in the reference too).
                self.forward = fwd[0].decode().split(",")
        p = self.process
        p.spawn_observed(self._serve_gen_read(), "coord_gr")
        p.spawn_observed(self._serve_gen_write(), "coord_gw")
        p.spawn_observed(self._serve_candidacy(), "coord_cd")
        p.spawn_observed(self._serve_get_leader(), "coord_gl")
        p.spawn_observed(self._serve_set_forward(), "coord_fw")
        p.spawn_observed(self._nominee_tick(), "coord_tick")

    async def _persist(self, key: bytes):
        if self._store is None:
            return
        from ..rpc.wire import encode_frame

        self._store.set(key, encode_frame(self.registry[key]))
        await self._store.commit()

    def interface(self) -> CoordinatorInterface:
        return CoordinatorInterface(
            gen_read=self._gr.ref(),
            gen_write=self._gw.ref(),
            candidacy=self._cd.ref(),
            get_leader=self._gl.ref(),
            set_forward=self._fw.ref(),
        )

    async def clear_forward(self):
        """Rejoin service: an address named in a NEW quorum must stop
        forwarding (the InitCoordinator path), or a reused retired member
        would answer every election with a stale pointer — two quorums
        pointing at each other can never elect anyone.

        Safe against the boot race: _boot checks the flag AFTER loading the
        registry from disk, so a clear issued while recovery is still in
        flight cannot be shadowed by the stale durable FORWARD_KEY."""
        self._forward_cleared = True
        self.forward = None
        self.registry[FORWARD_KEY] = (b"", ZERO_GEN, ZERO_GEN)
        await self._persist(FORWARD_KEY)
        self.nominee = None  # next tick renominates from live candidates

    async def _serve_set_forward(self):
        """Retire this coordinator: durably record the successor addresses
        and answer every future election request with the forward nominee
        (ref: ForwardRequest, Coordination.actor.cpp)."""
        while True:
            addrs, reply = await self._fw.pop()
            self.forward = list(addrs)  # fdblint: ignore[RACE004]: retirement is one-way — clear_forward and _boot order against it via _forward_cleared (see clear_forward docstring)
            self.registry[FORWARD_KEY] = (
                ",".join(addrs).encode(), ZERO_GEN, ZERO_GEN,
            )
            await self._persist(FORWARD_KEY)
            # Flush parked get_leader waiters with the forward nominee.
            self.nominee = _forward_info(self.forward)  # fdblint: ignore[RACE004]: nominee is a hint re-derived every election tick — a stale overwrite lasts one tick and renominates
            waiters, self._waiters = self._waiters, []
            for _known, w in waiters:
                w.send(self.nominee)
            reply.send(None)

    # --- generation register (ref localGenerationReg :125-160) ---
    async def _serve_gen_read(self):
        while True:
            req, reply = await self._gr.pop()
            value, rgen, wgen = self.registry.get(req.key, (None, ZERO_GEN, ZERO_GEN))
            if rgen < req.gen:
                rgen = req.gen
                self.registry[req.key] = (value, rgen, wgen)
                # The promise must survive a restart or a later stale write
                # could be accepted; durable BEFORE the reply.
                await self._persist(req.key)
            reply.send(GenReadReply(value=value, write_gen=wgen, read_gen=rgen))

    async def _serve_gen_write(self):
        while True:
            req, reply = await self._gw.pop()
            value, rgen, wgen = self.registry.get(req.key, (None, ZERO_GEN, ZERO_GEN))
            # Accept iff the writer's generation matches the newest promise
            # (ref: readGen <= gen && writeGen < gen, Coordination :148).
            if rgen <= req.gen and wgen < req.gen:
                self.registry[req.key] = (req.value, rgen, req.gen)
                await self._persist(req.key)  # durable before the ack
                reply.send(req.gen)  # accepted
            else:
                reply.send(max(rgen, wgen))  # conflict: newer gen promised

    # --- leader register (ref leaderRegister :203) ---
    def _recompute_nominee(self, now: float):
        if self.forward is not None:
            new = _forward_info(self.forward)
            if new != self.nominee:
                self.nominee = new
                waiters, self._waiters = self._waiters, []
                for _known, reply in waiters:
                    reply.send(self.nominee)
            return
        live = [info for info, exp in self.candidates.values() if exp > now]
        new = min(live) if live else None
        if new != self.nominee:
            self.nominee = new
            waiters, self._waiters = self._waiters, []
            for _known, reply in waiters:
                reply.send(self.nominee)

    async def _serve_candidacy(self):
        # Candidacy is lease refresh + immediate nomination report: parking
        # here would delay the candidate's own lease renewal past the TTL
        # and make nominations flap (observed; the reference separates the
        # heartbeat from the long-poll for the same reason).
        while True:
            req, reply = await self._cd.pop()
            now = self.process.network.loop.now()
            self.candidates[req.info.change_id] = (req.info, now + CANDIDATE_TTL)
            self._recompute_nominee(now)
            reply.send(self.nominee)

    async def _serve_get_leader(self):
        # Waiter list capped: abandoned long-polls (the poller timed out and
        # re-polled) would otherwise accumulate one entry per poll cycle for
        # as long as the nominee is stable.
        while True:
            req, reply = await self._gl.pop()
            known = req  # the change_id the client knows, or None
            if self.nominee is not None and self.nominee.change_id != known:
                reply.send(self.nominee)
            elif len(self._waiters) < 256:
                self._waiters.append((known, reply))
            else:
                reply.send(self.nominee)  # poller re-polls; stays bounded

    async def _nominee_tick(self):
        loop = self.process.network.loop
        while True:
            await loop.delay(NOMINEE_TICK)
            self._recompute_nominee(loop.now())


def quorum_state_key(addresses: List[str]) -> bytes:
    """The coordinated-state register key for ONE quorum membership.

    Derived from the member addresses, so OVERLAPPING old/new quorums in a
    coordinator change use DISTINCT keys on shared members — fencing the
    old set can never clobber the new set's manifest (the reference gets
    the same property by generating a new cluster id in the connection
    string on every changeQuorum, ManagementAPI.actor.cpp:684)."""
    import zlib

    blob = ",".join(addresses).encode()
    return b"cstate:%08x" % zlib.crc32(blob)


class CoordinatedState:
    """Quorum client over the coordinators' generation registers (ref:
    CoordinatedState.actor.cpp).  One instance per reader/writer session.

    With a CoordinatorSet (and no explicit key), the register key is
    derived from the membership via quorum_state_key — see its docstring
    for why overlapping quorums must not share a key."""

    def __init__(
        self,
        process: SimProcess,
        coordinators,
        key: Optional[bytes] = None,
    ):
        self.process = process
        # Pinned at construction: a session belongs to ONE quorum; a move
        # mid-session must surface as coordinated_state_conflict, not be
        # papered over by silently retargeting.
        self.coordinators = list(_resolve_coords(coordinators))
        if key is None:
            key = (
                quorum_state_key(coordinators.addresses)
                if isinstance(coordinators, CoordinatorSet)
                else b"cstate"
            )
        self.key = key
        self.gen = ZERO_GEN  # this session's generation, fixed at read()
        self._read_done = False

    @property
    def _quorum(self) -> int:
        return len(self.coordinators) // 2 + 1

    async def _quorum_replies(self, coros):
        """First `quorum` successful replies (tolerates a minority of
        failures)."""
        results = []
        pending = [self.process.spawn(c) for c in coros]
        while pending and len(results) < self._quorum:
            idx, val = await first_of(*pending)
            pending.pop(idx)
            if not isinstance(val, Exception):
                results.append(val)
        if len(results) < self._quorum:
            raise FdbError("coordinators_changed")
        return results

    async def _replicated_read(self, gen) -> GenReadReply:
        replies = await self._quorum_replies(
            _swallow(c.gen_read.get_reply(self.process, GenReadRequest(self.key, gen)))
            for c in self.coordinators
        )
        best = max(replies, key=lambda r: r.write_gen)
        max_rgen = max(r.read_gen for r in replies)
        return GenReadReply(
            value=best.value, write_gen=best.write_gen, read_gen=max_rgen
        )

    async def read(self) -> Optional[bytes]:
        """Two-phase (ref CoordinatedStateImpl::read): learn the newest
        generation, then plant our own (higher) read promise and read the
        authoritative value at it.  set() reuses that same generation, which
        is exactly what makes a later reader's promise doom our write."""
        probe = await self._replicated_read(ZERO_GEN)
        counter = max(probe.write_gen[0], probe.read_gen[0]) + 1
        salt = self.process.network.loop.rng.random_int(1, 1 << 30)
        self.gen = (counter, salt)
        rep = await self._replicated_read(self.gen)
        self._read_done = True
        return rep.value

    async def set(self, value: bytes):
        """Conditional write at the read-time generation (ref setExclusive:
        any register that promised a newer generation rejects ->
        coordinated_state_conflict)."""
        assert self._read_done, "CoordinatedState.set requires a prior read"
        replies = await self._quorum_replies(
            _swallow(
                c.gen_write.get_reply(
                    self.process, GenWriteRequest(self.key, value, self.gen)
                )
            )
            for c in self.coordinators
        )
        if any(r != self.gen for r in replies):
            raise FdbError("coordinated_state_conflict")


async def _swallow(fut):
    """Convert an RPC error into a returned exception (quorum logic counts
    failures instead of failing fast)."""
    try:
        return await fut
    except FdbError as e:
        return e



def _moved_to(info: LeaderInfo):
    """Forward addresses carried by a nominee, or None."""
    p = info.payload
    return p.get("moved_to") if isinstance(p, dict) else None

async def try_become_leader(
    process: SimProcess,
    coordinators,
    info: LeaderInfo,
    is_leader: AsyncVar,
):
    """Run candidacy forever: refresh leases, watch nominations; set
    `is_leader` True while this process holds a majority nomination (ref:
    tryBecomeLeaderInternal LeaderElection.actor.cpp:78).

    `coordinators` may be a CoordinatorSet: the set is re-read every round
    and forward replies retarget it, so candidacy survives a quorum change
    (ref: the ForwardRequest path in LeaderElection)."""
    loop = process.network.loop

    async def one_round(coords):
        # All coordinators in parallel: a refresh round must complete well
        # inside CANDIDATE_TTL or our own leases lapse and nominations flap.
        futs = [
            process.spawn(
                _swallow(
                    c.candidacy.get_reply(
                        process, CandidacyRequest(b"", info, info.change_id)
                    )
                )
            )
            for c in coords
        ]
        votes, forwards = 0, {}
        for f in futs:
            reply = await timeout_after(loop, f, POLL_INTERVAL, default=None)
            if reply is None or isinstance(reply, Exception):
                continue
            moved = _moved_to(reply)
            if moved is not None:
                key = tuple(moved)
                forwards[key] = forwards.get(key, 0) + 1
            elif reply.change_id == info.change_id:
                votes += 1
        return votes, forwards

    while True:
        coords = _resolve_coords(coordinators)
        quorum = len(coords) // 2 + 1
        votes, forwards = await one_round(coords)
        for addrs, n in forwards.items():
            if n >= quorum and isinstance(coordinators, CoordinatorSet):
                coordinators.retarget(list(addrs))
                votes = 0
                break
        is_leader.set(votes >= quorum)
        await loop.delay(POLL_INTERVAL)


async def monitor_leader(
    process: SimProcess,
    coordinators,
    leader_var: AsyncVar,
):
    """Poll coordinators; publish the majority nominee (ref:
    monitorLeaderInternal MonitorLeader.actor.cpp:427).

    `coordinators` may be a CoordinatorSet: a majority forward nominee
    retargets the set instead of being published — the client-side half of
    a coordinator quorum change (ref: MonitorLeader's connection-file
    rewrite on forward)."""
    loop = process.network.loop
    while True:
        coords = _resolve_coords(coordinators)
        counts: Dict[int, Tuple[int, LeaderInfo]] = {}
        for c in coords:
            known = leader_var.get().change_id if leader_var.get() else None
            fut = process.spawn(_swallow(c.get_leader.get_reply(process, known)))
            reply = await timeout_after(loop, fut, POLL_INTERVAL, default=None)
            if reply is None or isinstance(reply, Exception):
                continue
            n, _ = counts.get(reply.change_id, (0, reply))
            counts[reply.change_id] = (n + 1, reply)
        quorum = len(coords) // 2 + 1
        for change_id, (n, info) in counts.items():
            if n < quorum:
                continue
            moved = _moved_to(info)
            if moved is not None:
                if isinstance(coordinators, CoordinatorSet):
                    coordinators.retarget(list(moved))
                break
            if leader_var.get() is None or leader_var.get().change_id != change_id:
                leader_var.set(info)
            break
        await loop.delay(POLL_INTERVAL)
