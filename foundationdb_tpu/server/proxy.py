"""Proxy role: commit batching pipeline + read-version service.

Ref: MasterProxyServer.actor.cpp — batcher collects CommitTransactionRequests
(fdbrpc/batcher.actor.h), commitBatch :318 runs the phased pipeline
(get version from master -> resolve -> apply -> log -> reply), GRV service
transactionStarter :934.  The pipeline here is structured the same way:
batches overlap because ordering is carried by the sequencer's prevVersion
chain, which the resolver and the log each enforce independently — batch N+1
can be resolving while batch N is logging (ref: latestLocalCommitBatch*
NotifiedVersions :362,414,424).
"""

from __future__ import annotations

from typing import List, Tuple

from ..client.atomic import transform_versionstamp
from ..client.types import CommitTransactionRef, Mutation, MutationType
from ..conflict.types import COMMITTED, CONFLICT, TOO_OLD, TransactionConflictInfo
from ..flow.asyncvar import NotifiedVersion
from ..flow.error import ActorCancelled
from ..flow.eventloop import first_of
from ..flow.knobs import g_knobs
from ..flow.state_sanitizer import audited_dict
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from ..utils import RangeMap
from .interfaces import (
    TAG_ALL,
    TAG_DEFAULT,
    GetCommitVersionReply,
    GetKeyServersLocationsReply,
    GetRateInfoRequest,
    ProxyInterface,
    ResolveTransactionBatchRequest,
    ResolverInterface,
    SequencerInterface,
    TLogCommitRequest,
    TLogInterface,
)
from .log_system import tlogs_for_tag


def split_ranges_for_resolver(
    tr: TransactionConflictInfo, lo: bytes, hi
) -> TransactionConflictInfo:
    """Clip a transaction's conflict ranges to one resolver's key range
    (ref: ResolutionRequestBuilder.addTransaction
    MasterProxyServer.actor.cpp:280-303 — every resolver gets a slot for
    every transaction so reply indices align; ranges outside its space are
    simply absent)."""

    def clip(rng):
        b, e = rng
        cb = max(b, lo)
        ce = e if hi is None else min(e, hi)
        return (cb, ce) if cb < ce else None

    return TransactionConflictInfo(
        read_snapshot=tr.read_snapshot,
        read_ranges=[c for r in tr.read_ranges if (c := clip(r)) is not None],
        write_ranges=[c for r in tr.write_ranges if (c := clip(r)) is not None],
    )


class Proxy:
    def __init__(
        self,
        process: SimProcess,
        sequencer: SequencerInterface,
        resolvers: List[ResolverInterface],
        tlogs: List[TLogInterface],
        epoch_begin_version: int = 0,
        epoch: int = 0,
        resolver_split_keys: List[bytes] = None,
        ratekeeper=None,  # RatekeeperInterface or None (no admission control)
        system_map=None,  # recovered ([(b, e, [ids])], {id: StorageInterface})
        proxy_id: str = "proxy0",
        n_proxies: int = 1,
        n_satellites: int = 0,  # trailing logs that receive EVERY tag (ref:
        # satellite TLogs in the primary region — synchronous, in the ack
        # set, carrying the full stream for remote-region recovery)
    ):
        self.process = process
        self.epoch = epoch
        self.proxy_id = proxy_id
        self.n_proxies = n_proxies
        self.sequencer = sequencer
        self.resolvers = resolvers
        self.tlogs = tlogs
        # Key-space partition across resolvers (ref: keyResolvers
        # KeyRangeMap :185).  n resolvers need n-1 split points.
        from .system_keys import bounds_from_split_keys

        split = resolver_split_keys or []
        assert len(split) == len(resolvers) - 1, "need n-1 split keys"
        # [(lo, hi_or_None)] per resolver
        self.resolver_bounds = bounds_from_split_keys(split)
        # Superseded partitions still receiving ranges: [(bounds, until)].
        # After a split moves at version V, batches through
        # V + MVCC-window + in-flight-depth clip with the OLD bounds TOO, so
        # the new owner of a boundary range builds history while the old
        # owner still detects conflicts against writes it alone has seen
        # (ref: keyResolvers keeping multiple (version, resolver) entries
        # per range until the window expires, MasterProxyServer :185,
        # ApplyMetadataMutation's keyResolvers handling).
        self._old_bounds: List[Tuple[list, int]] = []
        self.ratekeeper = ratekeeper
        self.n_satellites = n_satellites
        # Set when the commit pipeline is unrecoverably wedged (a batch
        # died mid-phase); role_check reports it so the CC recovers.
        self.broken = False
        self.last_rate_info = None  # latest RateInfo fetched by the GRV loop
        self.committed = NotifiedVersion(epoch_begin_version)
        # Authoritative key -> storage-team map, maintained by intercepting
        # keyServers/serverList metadata mutations in the commits this proxy
        # processes (single-proxy stand-in for the reference's txnStateStore
        # + ApplyMetadataMutation; ref MasterProxyServer.actor.cpp:185,457).
        # Values are (route_team, tag_team) id-tuples: reads route to the
        # data holders (src during a move), mutations are tagged to every
        # current AND incoming holder (src + dest, so an AddingShard's
        # buffer sees the stream).  None = unsharded (no DD yet).
        self.key_servers = RangeMap(None)
        # Non-None while `\xff/dbLocked` holds a UID (ref: databaseLockedKey;
        # learned via the mutation stream or recovery-time map injection).
        self.locked_uid = None
        # Audited under FDB_TPU_STATE_SANITIZER: written by the commit
        # path's metadata intercept and recovery-time injection, read by
        # the read-routing path — a cross-actor shared map.
        self.server_list: dict = audited_dict(
            process.network.loop, "proxy.server_list"
        )
        if system_map is not None:
            entries, server_list = system_map
            for b, e, team in entries:
                self.key_servers.set_range(b, e, (tuple(team), tuple(team)))
            self.server_list = audited_dict(
                process.network.loop, "proxy.server_list", server_list
            )
        # Metadata applies in version order across THIS proxy's overlapped
        # batches (the own-version chain); versions granted to other proxies
        # in between are covered by the resolvers' state-mutation replies
        # (ref: resolution[0].stateMutations applied at
        # MasterProxyServer.actor.cpp:449-466 before own tag assignment).
        self._meta_version = NotifiedVersion(epoch_begin_version)
        self._last_own_version = epoch_begin_version
        # Local batch numbering serializes phase 1 so this proxy's versions
        # are granted in local batch order (ref: localBatchNumber and the
        # latestLocalCommitBatchResolving chain :362).
        self._local_batches = 0
        self._batch_resolving = NotifiedVersion(0)
        # Version through which resolve replies have been processed; rides
        # the next request so resolvers GC their reply caches (ref
        # lastReceivedVersion).
        self._last_received = epoch_begin_version
        self._commit_stream = RequestStream(process, "commit", well_known=True)
        self._grv_stream = RequestStream(process, "grv", well_known=True)
        self._loc_stream = RequestStream(
            process, "get_key_servers_locations", well_known=True
        )
        self._load_map_stream = RequestStream(
            process, "load_system_map", well_known=True
        )
        # Ref: ProxyStats MasterProxyServer.actor.cpp:45 + traceCounters.
        from ..flow.stats import CounterCollection

        self.stats = CounterCollection(f"Proxy{proxy_id}")
        for _c in ("batches", "committed", "conflicted", "too_old",
                   "grv_requests", "rejected_locked",
                   "grv_shed_batch", "grv_shed_default"):
            self.stats.counter(_c)  # pre-create: snapshots list them all
        # Proxy-observed latency distributions (batch arrival -> reply),
        # surfaced as status qos percentiles (ref: the commit/GRV latency
        # bands Status.actor.cpp derives from proxy metrics).
        from ..flow.stats import ContinuousSample

        _rng = process.network.loop.rng
        self.latency_samples = {
            "commit": ContinuousSample(_rng),
            "grv": ContinuousSample(_rng),
        }
        # Registry half of the pipeline (flow/metrics.py): ADOPTS the
        # stats counters above (one underlying Counter per verdict — call
        # sites increment once, the surfaces cannot drift) and adds the
        # batch-size/latency distributions.  One emitter actor replaces
        # trace_counters: emit_metrics emits the same per-counter
        # value+rate details under the same event name, plus gauges and
        # histogram summaries (two raters on one Counter would reset each
        # other's rate baseline).
        from ..flow.metrics import MetricsRegistry, emit_metrics

        self.metrics = MetricsRegistry(f"Proxy{proxy_id}", rng=_rng)
        for _c in self.stats.counters.values():
            self.metrics.adopt(_c)
        process.spawn(
            emit_metrics(self.metrics, process), "proxy_metrics_emit"
        )
        # Time-series sampler (ISSUE 10): bounded delta history of this
        # proxy's registry into the global hub (flow/timeseries.py).
        from ..flow.timeseries import spawn_sampler

        spawn_sampler(process, self.metrics.name, self.metrics)
        self._last_batch_cut = process.network.loop.now()
        process.spawn_observed(self._commit_batcher(), "proxy_batcher")
        # Always tick (not just multi-proxy): empty batches advance the
        # committed version with virtual time, which TaskBucket leases and
        # MVCC-window expiry depend on (ref: the master's version clock
        # advancing with wall time, masterserver getVersion :800-809).
        process.spawn_observed(self._idle_batch_ticker(), "proxy_idle_tick")
        process.spawn(self._serve_grv(), "proxy_grv")
        process.spawn_observed(self._serve_locations(), "proxy_locations")
        process.spawn_observed(self._serve_load_map(), "proxy_load_map")

    def _spawn_owned(self, coro, name: str):
        from ..rpc.stream import spawn_owned

        return spawn_owned(self, coro, name)

    def interface(self) -> ProxyInterface:
        return ProxyInterface(
            commit=self._commit_stream.ref(),
            get_consistent_read_version=self._grv_stream.ref(),
            get_key_servers_locations=self._loc_stream.ref(),
            load_system_map=self._load_map_stream.ref(),
        )

    async def _serve_load_map(self):
        """Recovery-time map injection (see ProxyInterface.load_system_map).
        Safe only before DD resumes writing metadata — the controller loads
        the map before publishing the cluster to clients."""
        while True:
            payload, reply = await self._load_map_stream.pop()
            entries, server_list = payload[0], payload[1]
            for b, e, team in entries:
                self.key_servers.set_range(b, e, (tuple(team), tuple(team)))
            self.server_list.update(server_list)
            if len(payload) > 2:
                # Recovery-time lock state (a lock must survive the
                # generation change that recruited this proxy).
                self.locked_uid = payload[2] or None
            reply.send(None)

    # --- key-location service (ref readRequestServer :1045) ---
    async def _serve_locations(self):
        while True:
            req, reply = await self._loc_stream.pop()
            out = []
            for b, e, v in self.key_servers.intersecting(req.begin, req.end):
                route = v[0] if v else None
                ifaces = (
                    [self.server_list[s] for s in route if s in self.server_list]
                    if route
                    else []
                )
                out.append((b, e, ifaces))
                if len(out) >= req.limit:
                    break
            reply.send(GetKeyServersLocationsReply(results=out))

    def _tags_for_mutation(self, m: Mutation) -> set:
        """Storage tags a mutation must reach (ref: the keyInfo tag lookup
        in commitBatch :547-600).  System-keyspace mutations broadcast
        (TAG_ALL — the private-mutation analog); unsharded ranges use
        TAG_DEFAULT (also on every log)."""
        tags: set = set()

        def range_tags(b, e):
            for _b, _e, v in self.key_servers.intersecting(b, e):
                if v and v[1]:
                    tags.update(v[1])
                else:
                    tags.add(TAG_DEFAULT)

        if m.type == MutationType.CLEAR_RANGE:
            b, e = m.param1, m.param2
            if e > b"\xff":
                tags.add(TAG_ALL)
            if b < b"\xff":
                range_tags(b, min(e, b"\xff"))
        elif m.param1 >= b"\xff":
            tags.add(TAG_ALL)
        else:
            v = self.key_servers[m.param1]
            if v and v[1]:
                tags.update(v[1])
            else:
                tags.add(TAG_DEFAULT)
        return tags

    def _intercept_metadata(self, m: Mutation, version: int = 0):
        """ApplyMetadataMutation analog for the proxy's own map."""
        from .system_keys import parse_metadata_mutation

        parsed = parse_metadata_mutation(m)
        if parsed is None:
            return
        if parsed[0] == "server":
            _kind, sid, iface = parsed
            self.server_list[sid] = iface
        elif parsed[0] == "resolver_split":
            from .system_keys import bounds_from_split_keys

            _kind, split = parsed
            if len(split) != len(self.resolvers) - 1:
                return  # malformed for this topology; ignore
            until = (
                version
                + g_knobs.server.max_write_transaction_life_versions
                + g_knobs.server.max_versions_in_flight
            )
            self._old_bounds.append((self.resolver_bounds, until))
            self.resolver_bounds = bounds_from_split_keys(split)
        elif parsed[0] == "lock":
            # Ref: applyMetadataMutations handling databaseLockedKey — the
            # proxy starts/stops rejecting non-lock-aware work.
            self.locked_uid = parsed[1] or None
        else:
            _kind, begin, src, dest, end = parsed
            # Reads route to the data holders: the sources while a move is
            # in flight (they serve until the settle), the team once settled.
            # A seed record (empty src) routes to dest — the shard is new.
            # Tags cover src AND dest so in-flight AddingShards see the
            # stream (ref: tag assignment from keyInfo incl. pending moves).
            route = tuple(src or dest)
            tags = tuple(sorted(set(src) | set(dest)))
            self.key_servers.set_range(begin, end, (route, tags))

    # --- GRV (ref transactionStarter :934) ---
    async def _serve_grv(self):
        """Batched read-version service: drain every queued request into one
        batch, spend the ratekeeper budget for the whole batch, answer all
        with one version (ref: transactionStarter draining its queue against
        the rate, MasterProxyServer.actor.cpp:934-1033)."""
        from ..flow.buggify import buggify
        from .interfaces import GRV_FLAG_PRIORITY_BATCH

        loop = self.process.network.loop
        budget = 1.0
        batch_budget = 1.0
        last_refill = loop.now()
        tps = None
        batch_tps = None
        last_fetch = -1e9
        deferred: list = []  # batch-priority replies awaiting lane budget
        from ..flow.trace import trace_batch

        # reply -> (debug_id, arrival time); survives lane deferral.
        grv_meta: dict = {}
        while True:
            if deferred and not self._grv_stream.is_ready():
                # Deferred batch-lane work but no new arrivals: tick the
                # budget forward instead of parking on the stream.
                await loop.delay(0.005)
                pairs = []
            else:
                req0, reply0 = await self._grv_stream.pop()
                pairs = [(req0, reply0)]
                while self._grv_stream.is_ready():
                    r, rep = await self._grv_stream.pop()
                    pairs.append((r, rep))
            self.stats.add("grv_requests", len(pairs))
            if pairs:
                self.metrics.histogram("grv_batch_size").add(len(pairs))
            if self.locked_uid is not None and pairs:
                # Ref: GRVs also fail database_locked unless lock-aware.
                from .interfaces import GRV_FLAG_LOCK_AWARE

                kept = []
                for r, rep in pairs:
                    if r is not None and not (r.flags & GRV_FLAG_LOCK_AWARE):
                        rep.send_error("database_locked")
                    else:
                        kept.append((r, rep))
                pairs = kept
            for r, rep in pairs:
                grv_meta[id(rep)] = (
                    getattr(r, "debug_id", None),
                    loop.now(),
                )
                trace_batch(
                    "TransactionDebug",
                    "MasterProxyServer.serveGrv.GotRequest",
                    getattr(r, "debug_id", None),
                )
            batch = [
                rep
                for r, rep in pairs
                if not (r is not None and r.flags & GRV_FLAG_PRIORITY_BATCH)
            ]
            lane = deferred + [
                rep
                for r, rep in pairs
                if r is not None and r.flags & GRV_FLAG_PRIORITY_BATCH
            ]
            deferred = []
            # Bounded admission queue (ISSUE 8): beyond the configured
            # depth the proxy SHEDS deterministically instead of queueing
            # without bound.  The batch-priority lane starves first (its
            # newest arrivals go first within the lane — FIFO for what
            # stays); only when the default lane alone overflows does it
            # shed too.  Both errors are retryable: clients re-enter with
            # exponential backoff + DeterministicRandom jitter (ref: the
            # proxy memory-limit rejection in transactionStarter).
            qmax = g_knobs.server.ratekeeper_grv_queue_max
            if len(batch) + len(lane) > qmax:
                from ..flow.testprobe import test_probe

                test_probe("grv_shed")
                keep_lane = max(0, qmax - len(batch))
                shed_lane, lane = lane[keep_lane:], lane[:keep_lane]
                shed_batch: list = []
                if len(batch) > qmax:
                    shed_batch, batch = batch[qmax:], batch[:qmax]
                for rep in shed_lane:
                    self.stats.add("grv_shed_batch")
                    grv_meta.pop(id(rep), None)
                    rep.send_error("batch_transaction_throttled")
                for rep in shed_batch:
                    self.stats.add("grv_shed_default")
                    grv_meta.pop(id(rep), None)
                    rep.send_error("proxy_memory_limit_exceeded")
            if buggify("proxy_grv_delay"):
                # BUGGIFY: stale-but-causal read versions (the committed
                # floor only rises) — exercises waitForVersion fast paths.
                await loop.delay(loop.rng.random01() * 0.02)
            if self.ratekeeper is not None:
                if loop.now() - last_fetch > 0.1:
                    try:
                        # The fetch carries this proxy's demand report
                        # (GetRateInfoRequest): queue depth for the status
                        # qos surface, and the passive commit p99 as the
                        # ratekeeper's fallback when no in-memory trace
                        # collector exists to reassemble latency chains.
                        info = await self.ratekeeper.get_rate.get_reply(
                            self.process,
                            GetRateInfoRequest(
                                proxy_id=self.proxy_id,
                                grv_queue_depth=len(batch) + len(lane),
                                commit_p99=(
                                    self.latency_samples["commit"]
                                    .percentile(0.99)
                                    or 0.0
                                ),
                            ),
                        )
                        tps = info.tps
                        batch_tps = getattr(info, "batch_tps", info.tps)
                        self.last_rate_info = info  # surfaced by status/qos
                    except Exception:  # noqa: BLE001 - rk down: keep old rate  # fdblint: ignore[ERR001]: ratekeeper unreachable — keeping the stale rate IS the degraded mode (a throttle beats none)
                        pass
                    last_fetch = loop.now()
                if tps is not None:
                    now = loop.now()
                    cap = max(float(len(batch)), tps * 0.1)
                    bcap = max(1.0, batch_tps * 0.1)
                    budget = min(budget + (now - last_refill) * tps, cap)
                    batch_budget = min(
                        batch_budget + (now - last_refill) * batch_tps, bcap
                    )
                    last_refill = now
                    while budget < len(batch):
                        # Floor the wait: a sub-float-resolution delay would
                        # not advance virtual time and the loop would spin.
                        await loop.delay(
                            max(
                                (len(batch) - budget) / max(tps, 1e-6), 5e-4
                            )
                        )
                        now = loop.now()
                        budget = min(budget + (now - last_refill) * tps, cap)
                        batch_budget = min(
                            batch_budget + (now - last_refill) * batch_tps,
                            bcap,
                        )
                        last_refill = now
                    budget -= len(batch)
                    # Batch lane: answer only what its budget affords NOW;
                    # the rest stays deferred (ref: the batch-priority GRV
                    # queue released strictly behind the default lane).
                    afford = int(batch_budget)
                    if afford < len(lane):
                        from ..flow.testprobe import test_probe

                        test_probe("grv_batch_deferred")
                        deferred = lane[afford:]
                        lane = lane[:afford]
                    batch_budget -= len(lane)
            batch = batch + lane
            if not batch:
                continue
            # GRV reply span (ISSUE 12): the causal-floor read + replies
            # for this drained batch.  Detached (the sequencer read
            # awaits); ended on both exits.
            from ..flow.spans import begin_span

            gspan = begin_span(
                "grv_batch", role=self.metrics.name,
                attrs={"n": len(batch)},
            )
            version = self.committed.get()
            if self.n_proxies > 1:
                # Another proxy may have committed (and acked) beyond this
                # proxy's chain; the sequencer's committed watermark covers
                # every proxy because each reports before replying to
                # clients (ref: GRV asking all proxies + confirming logs,
                # :956-1001 — the sequencer read is this rebuild's
                # equivalent causal floor).
                try:
                    version = max(
                        version,
                        await self.sequencer.get_committed_version.get_reply(
                            self.process, None
                        ),
                    )
                except Exception:  # noqa: BLE001 - sequencer died: this
                    # generation is ending; clients will retry against the
                    # next one.
                    for rep in batch:
                        grv_meta.pop(id(rep), None)
                        rep.send_error("broken_promise")
                    gspan.end(attrs={"error": "broken_promise"})
                    continue
            for rep in batch:
                did, t_arr = grv_meta.pop(id(rep), (None, loop.now()))
                self.latency_samples["grv"].add(loop.now() - t_arr)
                trace_batch(
                    "TransactionDebug",
                    "MasterProxyServer.serveGrv.Replied",
                    did,
                )
                rep.send(version)
            gspan.end(attrs={"version": version})

    async def _idle_batch_ticker(self):
        """Cut an EMPTY commit batch when no real batch has gone out for a
        while: the resolve round-trip delivers other proxies' state
        transactions (keeping this proxy's shard/tag map current even with
        zero commit traffic) and advances the resolver's per-proxy
        lastVersion so its retention GC can run (ref: the empty-batch tick
        in commitBatcher, MasterProxyServer.actor.cpp; Resolver GC
        :196-218)."""
        loop = self.process.network.loop
        interval = g_knobs.server.commit_batch_idle_interval
        while True:
            await loop.delay(interval)
            if loop.now() - self._last_batch_cut < interval:
                continue
            self._last_batch_cut = loop.now()
            self._local_batches += 1
            self._spawn_owned(
                self._commit_batch([], self._local_batches), "idle_batch"
            )

    # --- commit batching (ref batcher.actor.h + commitBatch :318) ---
    async def _commit_batcher(self):
        from ..flow.buggify import buggify

        loop = self.process.network.loop
        srv = g_knobs.server
        pending = None  # a pop() that lost the race to the window timer
        while True:
            first = await (pending or self._commit_stream.pop())
            pending = None
            batch = [first]
            # BUGGIFY: single-transaction batches maximize pipeline overlap
            # and per-batch edge cases (ref: buggified batch knobs).
            batch_max = (
                1
                if buggify("proxy_tiny_batch")
                else srv.commit_transaction_batch_count_max
            )
            deadline = loop.now() + srv.commit_transaction_batch_interval
            while (
                len(batch) < batch_max
                and loop.now() < deadline
            ):
                nxt = self._commit_stream.pop()
                timer = loop.delay(deadline - loop.now())
                idx, val = await first_of(nxt, timer)
                if idx == 1:
                    # Window closed.  `nxt` is still registered with the
                    # stream; it MUST be the next batch's first element or
                    # the request it eventually receives would be lost.
                    pending = nxt
                    break
                loop.cancel_timer(timer)
                batch.append(val)
            self._last_batch_cut = loop.now()
            self._local_batches += 1
            self._spawn_owned(
                self._commit_batch(batch, self._local_batches), "commit_batch"
            )

    async def _commit_batch(self, batch: List[Tuple], local_batch: int):
        ctx: dict = {}
        try:
            await self._commit_batch_impl(batch, local_batch, ctx)
        except ActorCancelled:
            # Role teardown cancelling an in-flight batch is NOT a pipeline
            # break: re-raise so the task dies cleanly (Reply.__del__
            # breaks the clients' promises; the new generation serves
            # their retries).
            raise
        except Exception as e:  # noqa: BLE001
            # The failed batch's (prev, version) pair is now a PERMANENT
            # hole in the prevVersion chain: the logs wait for it forever,
            # wedging every later batch even when the failure was a
            # transient transport error on a live role.  The reference's
            # proxy actor dies here (recovery follows); mark this proxy
            # broken so the CC's role_check starts the recovery the ping
            # sweep cannot see (the process is alive and pinging fine).
            self.broken = True
            from ..flow.testprobe import test_probe

            test_probe("proxy_pipeline_broken")
            from ..flow.trace import TraceEvent

            TraceEvent("ProxyCommitPipelineBroken", severity=30).detail(
                "proxy", self.proxy_id
            ).detail("error", getattr(e, "name", repr(e))).log()
            # Unwedge the local chains so later batches don't deadlock
            # behind this one: they fail fast (the same dead role) and their
            # clients get commit_unknown_result instead of hanging until
            # failure detection replaces the generation.  Skipping this
            # batch's metadata application is safe: nothing after it can
            # durably commit in this generation (phase 4 requires ALL logs),
            # and recovery rebuilds the map from storage ownership.
            self._batch_resolving.set(
                max(self._batch_resolving.get(), local_batch)
            )
            if "version" in ctx:
                self._meta_version.set(
                    max(self._meta_version.get(), ctx["version"])
                )
            # A phase RPC failed (e.g. resolver/tlog died mid-batch).  The
            # outcome is genuinely unknown — the log may or may not have made
            # it durable — so every client gets commit_unknown_result (ref:
            # NativeAPI :2430-2449; generation recovery replaces this proxy).
            for _req, reply in batch:
                reply.send_error("commit_unknown_result")

    async def _commit_batch_impl(
        self, batch: List[Tuple], local_batch: int, ctx: dict = None
    ):
        from ..flow.eventloop import wait_for_all
        from ..flow.spans import NULL_SPAN, begin_span
        from ..flow.trace import trace_batch

        loop0 = self.process.network.loop
        t_start = loop0.now()
        # Batch-level debug id: the first sampled transaction's (ref:
        # commitBatch folding member debugIDs into one batch UID :340).
        batch_debug = next(
            (req.debug_id for req, _r in batch if req.debug_id is not None),
            None,
        )
        # Batch span (ISSUE 12): real batches only — the idle ticker cuts
        # an empty batch every commit_batch_idle_interval, which would
        # bury the ring in no-payload spans.  Phase children are created
        # with EXPLICIT parents (each crosses awaits, where the hub's
        # current-span stack is not valid).
        bspan = (
            begin_span(
                "commit_batch", role=self.metrics.name,
                attrs={"n_txn": len(batch), "local_batch": local_batch},
            )
            if batch
            else NULL_SPAN
        )
        def _phase(name):
            # Phase child span — only under a real batch span (an empty
            # idle batch records nothing).
            if bspan is NULL_SPAN:
                return NULL_SPAN
            return begin_span(name, parent=bspan)

        trace_batch(
            "CommitDebug", "MasterProxyServer.commitBatch.Before", batch_debug
        )
        # Database lock (ref: commitBatch rejecting non-lock-aware txns
        # while databaseLockedKey is set).  Rejected BEFORE resolution so
        # their conflict ranges never enter history; the possibly-empty
        # remainder still runs the pipeline to keep the version chains
        # advancing.
        if self.locked_uid is not None:
            from .interfaces import COMMIT_FLAG_LOCK_AWARE

            kept = []
            for req, reply in batch:
                if req.flags & COMMIT_FLAG_LOCK_AWARE:
                    kept.append((req, reply))
                else:
                    self.stats.add("rejected_locked")
                    reply.send_error("database_locked")
            batch = kept
        self.stats.add("batches")
        if batch:
            # Real batches only: the idle ticker cuts empty batches every
            # commit_batch_idle_interval, which would bury the size/latency
            # distributions under zeros (the GRV path guards identically).
            self.metrics.histogram("commit_batch_size").add(len(batch))
        # Phase 1: commit version from the sequencer, serialized in local
        # batch order so this proxy's versions are monotone in batch order
        # (ref: the localBatchNumber chain :362; GetCommitVersionRequest ->
        # masterserver getVersion :783).
        pspan = _phase("get_version")
        await self._batch_resolving.when_at_least(local_batch - 1)
        gv: GetCommitVersionReply = await self.sequencer.get_commit_version.get_reply(
            self.process, self.epoch  # fenced: only this generation is served
        )
        version, prev = gv.version, gv.prev_version
        pspan.end(attrs={"version": version})
        bspan.annotate("version", version)
        trace_batch(
            "CommitDebug",
            "MasterProxyServer.commitBatch.GotCommitVersion",
            batch_debug,
        )
        if ctx is not None:
            ctx["version"] = version
        own_prev, self._last_own_version = self._last_own_version, version
        self._batch_resolving.set(local_batch)
        from ..flow.buggify import buggify

        if buggify("proxy_resolve_delay"):
            # BUGGIFY: let a LATER batch reach the resolvers first —
            # exercises the prevVersion reorder wait (Resolver :104-115).
            loop = self.process.network.loop
            await loop.delay(loop.rng.random01() * 0.02)

        # Phase 2: resolution.  One ResolveTransactionBatchRequest per
        # resolver; each resolver sees the ranges in its key space (the
        # mesh-sharded ConflictSet clips on device) and verdicts are
        # min-combined (ref ResolutionRequestBuilder :237, combine :492-499).
        # Transactions touching \xff are state transactions: their mutations
        # ride the request so the resolvers can hand them to other proxies
        # (ref ResolutionRequestBuilder :307).
        infos = [
            TransactionConflictInfo(
                read_snapshot=req.transaction.read_snapshot,
                read_ranges=list(req.transaction.read_conflict_ranges),
                write_ranges=list(req.transaction.write_conflict_ranges),
            )
            for (req, _reply) in batch
        ]
        state_txns = [
            (t, list(req.transaction.mutations))
            for t, (req, _reply) in enumerate(batch)
            if any(
                m.param1 >= b"\xff"
                or (m.type == MutationType.CLEAR_RANGE and m.param2 > b"\xff")
                for m in req.transaction.mutations
            )
        ]
        # Clip per the current partition, UNIONed with any superseded
        # partitions whose overlap window still covers this version (see
        # _old_bounds).  Filter per batch WITHOUT mutating: a later-version
        # batch can reach this point before an earlier in-flight batch
        # clips, and pruning here would strip an overlay the earlier batch
        # still needs (its boundary ranges would reach only the new owner,
        # missing old-owner-only history).  Pruning happens in phase 3,
        # where the per-proxy version chain guarantees every earlier batch
        # has already clipped.
        bound_sets = [self.resolver_bounds] + [
            b for b, until in self._old_bounds if version <= until
        ]

        def clip_for(ri: int, tr: TransactionConflictInfo):
            lo, hi = bound_sets[0][ri]
            out = split_ranges_for_resolver(tr, lo, hi)
            for bounds in bound_sets[1:]:
                lo2, hi2 = bounds[ri]
                extra = split_ranges_for_resolver(tr, lo2, hi2)
                # Deterministic dedupe (dict preserves insertion order).
                out.read_ranges = list(
                    dict.fromkeys(out.read_ranges + extra.read_ranges)
                )
                out.write_ranges = list(
                    dict.fromkeys(out.write_ranges + extra.write_ranges)
                )
            return out

        # Clipped per-resolver transaction views, retained past the
        # resolve round-trip: an abort witness names a read-range ordinal
        # WITHIN the clipped txn the owning resolver saw, so decoding it
        # back to key bytes needs exactly this list (ISSUE 17).
        clipped = [
            [clip_for(ri, tr) for tr in infos]
            for ri in range(len(self.resolvers))
        ]
        pspan = _phase("resolution")
        replies = await wait_for_all(
            [
                r.resolve.get_reply(
                    self.process,
                    ResolveTransactionBatchRequest(
                        prev_version=prev,
                        version=version,
                        last_received_version=self._last_received,
                        transactions=clipped[ri],
                        state_txns=state_txns,
                        proxy_id=self.proxy_id,
                        epoch=self.epoch,
                        debug_id=batch_debug,
                    ),
                )
                for ri, r in enumerate(self.resolvers)
            ]
        )
        statuses = [
            min(rep.committed[t] for rep in replies) for t in range(len(batch))
        ]
        pspan.end(attrs={"n_resolvers": len(self.resolvers)})
        trace_batch(
            "CommitDebug",
            "MasterProxyServer.commitBatch.AfterResolution",
            batch_debug,
        )

        # Phase 3: post-resolution processing, strictly in this proxy's own
        # version order: first the OTHER proxies' state transactions for the
        # versions in between (from the resolvers' replies, committed on
        # every resolver — ref :449-466), then own versionstamp substitution
        # (ref :269-274), own metadata application, THEN per-tag assembly —
        # so a batch's tags are computed against every earlier batch's (and
        # its own) metadata, exactly like the reference's
        # applyMetadataMutations :457 before tag assignment :547-600.
        # Without the ordering, a write pipelined behind a startMove could
        # miss the destination's tag and silently diverge the new replica.
        await self._meta_version.when_at_least(own_prev)
        # Safe overlay prune: every own batch with a smaller version has
        # finished phase 2 by now (phase 3 is version-ordered and phase 2
        # precedes it), and future batches get larger versions.
        self._old_bounds = [
            (b, until) for b, until in self._old_bounds if until >= version
        ]
        for vi, (sv, txns) in enumerate(replies[0].state_mutations):
            for ti, (committed, muts) in enumerate(txns):
                if committed and all(
                    rep.state_mutations[vi][1][ti][0] for rep in replies[1:]
                ):
                    for m in muts:
                        self._intercept_metadata(m, version=sv)
        self._last_received = max(self._last_received, version)
        # Version-ordered lock fence: the state transactions just applied
        # include any lock committed at a version below this batch, so a
        # non-lock-aware transaction can never commit at a version above
        # the lock's (the upfront check at batch entry is only the cheap
        # fast path).  Rejected txns' conflict ranges already entered the
        # resolvers' history as committed — the safe direction: at worst a
        # later reader conflicts spuriously; their MUTATIONS never reach a
        # log.
        rejected_locked: set = set()
        if self.locked_uid is not None:
            from .interfaces import COMMIT_FLAG_LOCK_AWARE

            # State transactions are EXEMPT here: their metadata already
            # travelled to every proxy via the resolvers' state_mutations
            # with committed=True — rejecting only our local copy would
            # diverge the proxies' shard/lock maps.  They remain subject to
            # the batch-entry check; the residual same-window race admits a
            # rare system-keyspace commit above the lock version, applied
            # CONSISTENTLY everywhere (user-keyspace fencing is exact).
            state_idx = {t for t, _muts in state_txns}
            for t, ((req, _reply), status) in enumerate(zip(batch, statuses)):
                if (
                    status == COMMITTED
                    and t not in state_idx
                    and not (req.flags & COMMIT_FLAG_LOCK_AWARE)
                ):
                    rejected_locked.add(t)
        tagged: dict = {}
        seq = 0
        for t, ((req, _reply), status) in enumerate(zip(batch, statuses)):
            if status != COMMITTED or t in rejected_locked:
                continue
            for m in req.transaction.mutations:
                if m.type == MutationType.SET_VERSIONSTAMPED_KEY:
                    m = Mutation(
                        MutationType.SET_VALUE,
                        transform_versionstamp(m.param1, version, t),
                        m.param2,
                    )
                elif m.type == MutationType.SET_VERSIONSTAMPED_VALUE:
                    m = Mutation(
                        MutationType.SET_VALUE,
                        m.param1,
                        transform_versionstamp(m.param2, version, t),
                    )
                self._intercept_metadata(m, version=version)
                for tag in self._tags_for_mutation(m):
                    tagged.setdefault(tag, []).append((seq, m))
                seq += 1
        self._meta_version.set(version)

        # Phase 4: push each tag to its logs (ref logSystem->push with
        # policy-selected tlog subsets); every log gets every version so
        # the prevVersion chain holds.  Durable when ALL acked.
        n = len(self.tlogs)
        routing_n = n - self.n_satellites  # tag placement over regular logs
        per_log: List[dict] = [{} for _ in range(n)]
        for tag, muts in tagged.items():
            for li in tlogs_for_tag(tag, routing_n):
                per_log[li][tag] = muts
            # Satellites carry every tag (the full stream, synchronously
            # in the ack set — the remote region's recovery source).
            for li in range(routing_n, n):
                per_log[li][tag] = muts
        pspan = _phase("log_push")
        await wait_for_all(
            [
                tl.commit.get_reply(
                    self.process,
                    TLogCommitRequest(
                        prev_version=prev,
                        version=version,
                        tagged=per_log[li],
                        epoch=self.epoch,
                        known_committed=self.committed.get(),
                        debug_id=batch_debug,
                    ),
                )
                for li, tl in enumerate(self.tlogs)
            ]
        )
        pspan.end(attrs={"n_logs": len(self.tlogs)})
        trace_batch(
            "CommitDebug",
            "MasterProxyServer.commitBatch.AfterLogPush",
            batch_debug,
        )

        from ..flow import sim_validation

        sim_validation.mark_at_least(
            self.process.network.loop, "acked_commit", version
        )
        # Phase 5: report + reply (ref :636-677).  NOTE: metadata applied
        # pre-push (phase 3) — if the push then fails, the map may reflect a
        # handoff whose commit outcome is unknown; that batch also wedges
        # the log's version chain, so the generation is replaced and the
        # recovered proxy rebuilds its map from storage ownership
        # (get_owned_meta), which resolves either way.
        await self.sequencer.report_committed.get_reply(self.process, version)
        if version > self.committed.get():
            self.committed.set(version)
        if batch:
            # Real batches only (both latency surfaces): the idle ticker's
            # empty batches run the same pipeline and would dominate the
            # qos percentiles with no-payload floor samples.
            self.latency_samples["commit"].add(loop0.now() - t_start)
            self.metrics.histogram("commit_batch_seconds").add(
                loop0.now() - t_start
            )
            if any(getattr(rep, "degraded", False) for rep in replies):
                # A resolver absorbed a device fault (CPU retry) inside
                # this batch: tag its latency separately so degraded-mode
                # cost is visible next to the healthy distribution.
                self.metrics.histogram("commit_batch_seconds_degraded").add(
                    loop0.now() - t_start
                )
        # The stats counters below ARE the registry counters (adopted in
        # __init__): one increment per verdict, and both telemetry
        # surfaces read the same value — a lock-rejected txn that resolved
        # COMMITTED counts as rejected_locked, never committed.
        pspan = _phase("reply")
        n_committed = 0
        for t, ((req, reply), status) in enumerate(zip(batch, statuses)):
            trace_batch(
                "CommitDebug",
                "MasterProxyServer.commitBatch.AfterReply",
                req.debug_id,
            )
            if t in rejected_locked:
                self.stats.add("rejected_locked")
                reply.send_error("database_locked")
            elif status == COMMITTED:
                self.stats.add("committed")
                n_committed += 1
                reply.send(version)
            elif status == TOO_OLD:
                self.stats.add("too_old")
                reply.send_error("transaction_too_old")
            else:
                self.stats.add("conflicted")
                reply.send_error(
                    "not_committed",
                    detail=self._conflict_cause(t, replies, clipped, version),
                )
        pspan.end(attrs={"committed": n_committed})
        bspan.end(attrs={"committed": n_committed})

    def _conflict_cause(self, t, replies, clipped, batch_version):
        """Combine txn `t`'s abort witnesses across the resolvers into the
        structured not_committed cause (ISSUE 17): version = MAX
        conflicting write version over the resolvers that aborted it (the
        txn must re-read past ALL of them), range = the losing read range
        reported by the lowest-indexed conflicting resolver — the same
        deterministic tie-break the sharded set's in-core combine uses,
        decoded to key bytes via that resolver's clipped view.
        retry_version is the BATCH version: the newest version at which
        this conflict decision is complete (it includes the winning write
        and every commit before it, and is reported committed before the
        error reply is sent), so a retry reading there observes
        everything that aborted us without a fresh GRV round-trip.  None
        when no witness arrived (FDB_TPU_WITNESS=0 or pre-witness
        resolvers): the client then sees the reference's bare
        not_committed."""
        version = None
        first = None
        for ri, rep in enumerate(replies):
            wits = rep.witnesses or []
            wit = wits[t] if t < len(wits) else None
            if wit is None or rep.committed[t] != CONFLICT:
                continue
            version = wit[0] if version is None else max(version, wit[0])
            if first is None:
                first = (ri, wit[1])
        if first is None:
            return None
        ri, idx = first
        rr = clipped[ri][t].read_ranges
        rng = rr[idx] if idx < len(rr) else None
        return {
            "version": int(version),
            "retry_version": int(batch_version),
            "range": (rng[0], rng[1]) if rng is not None else None,
        }
