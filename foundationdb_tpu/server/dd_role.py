"""Self-driving DataDistribution: the continuously-running control loop
over the transactional primitives in `data_distribution.py`.

Ref: fdbserver/DataDistribution.actor.cpp:1237 (teamTracker reacting to
storage failures), fdbserver/DataDistributionTracker.actor.cpp (shard
split/merge on byte-sample cadence), fdbserver/DataDistributionQueue.actor.cpp
(RelocateShard queue with priorities and a parallelism limit).

The reference's DD is a live role: nothing outside it calls MoveKeys — the
teamTracker notices a degraded team and *enqueues* a relocation, the
tracker notices an oversized shard and splits it, and the queue executes a
bounded number of moves at once, highest priority first.  This module is
that control loop for the rebuild: `DataDistributionRole` owns a
`DataDistributor` (a client of the database, as in the reference) and runs

  - a storage liveness probe (consecutive-failure counting over cheap
    get_version RPCs — DD's local analog of the failure broadcast),
  - a team tracker that heals shards listing failed/excluded members back
    to full team width using the healthiest spares,
  - a shard tracker driving auto_split / auto_merge on a cadence and
    enqueueing count-rebalancing moves after splits,
  - an exclusion tracker polling `\xff/conf/excluded/...`,
  - N queue workers executing moves.

Every actor is convergence-based: failed moves are dropped and re-derived
from the authoritative shard map on the next tracker round, so crashes,
re-recruitments, and racing operators cannot wedge the loop.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..flow.asyncvar import AsyncVar
from ..flow.buggify import buggify
from ..flow.error import ActorCancelled, FdbError
from ..flow.eventloop import timeout_after
from ..flow.knobs import g_knobs
from ..flow.testprobe import test_probe
from ..flow.trace import TraceEvent
from . import system_keys as sk
from .data_distribution import DataDistributor

# Relocation priorities (ref: SERVER_KNOBS->PRIORITY_TEAM_UNHEALTHY et al,
# DataDistributionQueue.actor.cpp — higher runs first).
PRIORITY_TEAM_UNHEALTHY = 200
PRIORITY_EXCLUSION = 150
PRIORITY_REDRIVE = 100  # finish a move another actor started but abandoned
PRIORITY_REBALANCE = 50


@dataclass
class RelocateShard:
    """One queued move: shard at `begin` should end up on `dest_team`."""

    begin: bytes
    dest_team: List[str]
    priority: int
    reason: str = ""


class DataDistributionRole:
    """The live DD actor set.  Construct with a DataDistributor (which
    carries the Database handle and the id->interface map) and call
    `start()`; `stop()` cancels every actor (the CC does this when a new
    generation retires the old singleton)."""

    def __init__(self, dd: DataDistributor, tlogs: list = None, active_fn=None):
        self.dd = dd
        self.loop = dd.loop
        self.process = dd.db.process
        self.tlogs = list(tlogs or [])
        # Singleton fencing: the CC passes a generation/leadership check so
        # a superseded DD (old generation, or a CC that lost the election)
        # stops initiating moves (ref: the dataDistributor being re-recruited
        # per master generation).
        self.active = active_fn or (lambda: True)
        self.failed: Set[str] = set()
        self.excluded: Set[str] = set()
        self._fail_counts: Dict[str, int] = {}
        self._queue: Dict[bytes, RelocateShard] = {}
        self._queue_wake = AsyncVar(0)
        self._inflight: Set[bytes] = set()
        self._tasks: list = []
        self.moves_done = 0
        self.heals_done = 0
        self.splits_done = 0
        self.merges_done = 0
        k = g_knobs.server
        self.ping_interval = k.dd_ping_interval
        self.tracker_interval = k.dd_tracker_interval
        if buggify("dd_aggressive_tracker"):
            # Rare-path activation: a hyperactive tracker shakes out races
            # between healing, splitting, and user commits.
            self.tracker_interval = min(0.25, self.tracker_interval)

    # --- lifecycle ---
    def start(self) -> "DataDistributionRole":
        spawn = self.process.spawn
        self._tasks = [
            spawn(self._probe_loop(), "dd_probe"),
            spawn(self._team_tracker(), "dd_teams"),
            spawn(self._shard_tracker(), "dd_tracker"),
            spawn(self._exclusion_tracker(), "dd_exclusions"),
        ]
        for i in range(g_knobs.server.dd_move_parallelism):
            self._tasks.append(spawn(self._queue_worker(), f"dd_queue{i}"))
        return self

    def stop(self):
        for t in self._tasks:
            t.cancel()
        self._tasks = []

    # --- storage liveness (ref: teamTracker's server failure inputs) ---
    async def _probe_loop(self):
        """Cheap get_version pings with consecutive-failure counting; a
        storage is `failed` after dd_failure_detections misses in a row and
        healthy again on the first success (the sim fabric has latency
        noise and BUGGIFY delays, so one miss must not trigger a heal)."""
        detections = g_knobs.server.dd_failure_detections
        while True:
            if not self.active():
                await self.loop.delay(self.ping_interval)
                continue
            for sid, iface in sorted(self.dd.storages.items()):
                ok = await self._ping(iface)
                if ok:
                    self._fail_counts[sid] = 0
                    self.failed.discard(sid)
                else:
                    n = self._fail_counts.get(sid, 0) + 1
                    self._fail_counts[sid] = n
                    if n >= detections and sid not in self.failed:
                        test_probe("dd_storage_declared_failed")
                        TraceEvent("DDStorageFailed").detail(
                            "id", sid
                        ).log()
                        self.failed.add(sid)
            await self.loop.delay(self.ping_interval)

    async def _ping(self, iface) -> bool:
        task = self.process.spawn(
            self._swallow(iface.get_version.get_reply(self.process, None))
        )
        try:
            v = await timeout_after(
                self.loop, task, g_knobs.server.dd_ping_timeout
            )
            return isinstance(v, int)
        except ActorCancelled:
            raise
        except Exception:  # fdblint: ignore[ERR001]: liveness probe — ANY failure IS the negative verdict it reports
            return False
        finally:
            # A wedged-but-alive storage never replies: without this the
            # probe loop would strand one orphan task per ping interval.
            if not task.is_ready():
                task.cancel()

    async def _swallow(self, fut):
        try:
            return await fut
        except FdbError:
            return None

    # --- team tracker (ref: DataDistribution.actor.cpp:1237) ---
    async def _team_tracker(self):
        """Each round: any settled shard whose team lists a failed or
        excluded member (with at least one healthy survivor) is enqueued
        for relocation back to its original width, using the least-loaded
        healthy spares as replacements."""
        while True:
            try:
                if self.active():
                    await self._team_round()
            except ActorCancelled:
                raise
            except (FdbError, TimeoutError):
                pass  # mid-recovery; re-derive next round
            await self.loop.delay(self.tracker_interval)

    async def _team_round(self):
        bad = self.failed | self.excluded
        shard_map = await self.dd.read_shard_map()
        counts = self._shard_counts(shard_map)
        for b, _e, team, dest in shard_map:
            members = list(dest or team)
            sick = [s for s in members if s in bad]
            if b in self._inflight or b in self._queue:
                continue
            if not sick:
                if dest:
                    # Abandoned move (a previous DD singleton was stopped
                    # between startMove and finish): re-drive it to done —
                    # dd.move() recognizes the same in-flight destination
                    # and completes it rather than restarting.
                    test_probe("dd_move_redriven")
                    self._enqueue(
                        RelocateShard(
                            b, list(dest), PRIORITY_REDRIVE, reason="redrive"
                        )
                    )
                continue
            survivors = [s for s in members if s not in bad]
            if not survivors:
                TraceEvent("DDShardUnhealable", severity=30).detail(
                    "begin", b
                ).detail("team", members).log()
                continue
            spares = self._pick_spares(
                len(members) - len(survivors), exclude=set(members), counts=counts
            )
            # Account the picks so several heals in one round spread over
            # the spares instead of piling onto a single idlest storage.
            for sid in spares:
                counts[sid] = counts.get(sid, 0) + 1
            new_team = survivors + spares
            prio = (
                PRIORITY_TEAM_UNHEALTHY
                if any(s in self.failed for s in sick)
                else PRIORITY_EXCLUSION
            )
            test_probe("dd_heal_enqueued")
            self._enqueue(
                RelocateShard(b, new_team, prio, reason=f"unhealthy:{sick}")
            )

    def _healthy(self) -> List[str]:
        return [
            sid
            for sid in self.dd.storages
            if sid not in self.failed and sid not in self.excluded
        ]

    def _shard_counts(self, shard_map) -> Dict[str, int]:
        """Settled user-shard count per healthy storage (zero included, so
        empty spares attract load)."""
        counts = {sid: 0 for sid in self._healthy()}
        for b, _e, team, dest in shard_map:
            if dest or b >= b"\xff":
                continue
            for sid in team:
                if sid in counts:
                    counts[sid] += 1
        return counts

    def _pick_spares(self, n: int, exclude: Set[str], counts: Dict[str, int]):
        """Up to n healthy storages not in `exclude`, fewest shards first
        (ref: team selection preferring the least-utilized servers)."""
        pool = sorted(
            (sid for sid in self._healthy() if sid not in exclude),
            key=lambda s: (counts.get(s, 0), s),
        )
        return pool[:n]

    # --- shard tracker (ref: DataDistributionTracker.actor.cpp) ---
    async def _shard_tracker(self):
        """Cadenced split / merge / rebalance.  Split and merge are
        metadata-only transactions from data_distribution.py; rebalance
        enqueues real moves at the lowest priority."""
        while True:
            await self.loop.delay(self.tracker_interval)
            if not self.active():
                continue
            try:
                await self._refresh_storages()
                split = await self.dd.auto_split(g_knobs.server.dd_shard_max_bytes)
                if split:
                    test_probe("dd_auto_split_fired")
                    self.splits_done += len(split)
                merged = await self.dd.auto_merge(g_knobs.server.dd_shard_min_bytes)
                if merged:
                    test_probe("dd_auto_merge_fired")
                    self.merges_done += len(merged)
                await self._rebalance_round()
            except ActorCancelled:
                raise
            except (FdbError, TimeoutError, AssertionError):
                # Mid-recovery, or racing an operator move; next round
                # re-derives from the authoritative map.
                continue

    async def _refresh_storages(self):
        """Fold `\xff/serverList/` into the id->interface map so storages
        registered after this role started (re-recruitments, new spares)
        become heal targets (ref: DD reading serverListKeys)."""

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.options["lock_aware"] = True
            return await tr.get_range(sk.SERVER_LIST_PREFIX, sk.SERVER_LIST_END)

        for k, v in await self.dd.db.run(txn):
            sid = sk.server_list_id(k)
            if sid not in self.dd.storages:
                self.dd.storages[sid] = sk.decode_server_entry(v)

    async def _rebalance_round(self):
        """Count-based load balance: when the busiest healthy storage has
        >= 2 more settled user shards than the idlest, move one shard off
        it, swapping busiest->idlest in that shard's team (ref: the
        BgDDMountainChopper/valley-filler rebalancers,
        DataDistributionQueue.actor.cpp)."""
        shard_map = await self.dd.read_shard_map()
        counts = self._shard_counts(shard_map)
        if len(counts) < 2:
            return
        busiest = max(counts, key=lambda s: (counts[s], s))
        idlest = min(counts, key=lambda s: (counts[s], s))
        if counts[busiest] - counts[idlest] < 2:
            return
        for b, _e, team, dest in shard_map:
            if dest or b >= b"\xff":
                continue
            if busiest not in team or idlest in team:
                continue
            if b in self._inflight or b in self._queue:
                continue
            new_team = [idlest if s == busiest else s for s in team]
            test_probe("dd_rebalance_enqueued")
            self._enqueue(
                RelocateShard(
                    b, new_team, PRIORITY_REBALANCE,
                    reason=f"rebalance:{busiest}->{idlest}",
                )
            )
            return  # one rebalancing move per round

    # --- exclusions (ref: DD watching excludedServersKeys) ---
    async def _exclusion_tracker(self):
        from ..client.management import get_excluded_servers
        from .interfaces import TLogPopRequest

        unregistered: Set[str] = set()  # acked tag unregisters
        while True:
            if not self.active():
                await self.loop.delay(self.tracker_interval)
                continue
            try:
                now_excluded = set(await get_excluded_servers(self.dd.db))
            except (FdbError, TimeoutError):
                await self.loop.delay(self.tracker_interval)
                continue
            for sid in sorted(now_excluded - self.excluded):
                test_probe("dd_exclusion_observed")
                TraceEvent("DDExclusionObserved").detail("id", sid).log()
            self.excluded = now_excluded
            # Targets: excluded servers AND probe-declared-dead ones.  The
            # CC unregisters dead tags once at recovery, but that send is
            # best-effort (a dropped reply would otherwise pin one tlog's
            # trim floor until an unrelated recovery); this loop is the
            # convergent owner.  A server dropped from both sets (healthy
            # again / re-included) leaves `unregistered` so a LATER death
            # re-unregisters it — re-sending is idempotent, and a revived
            # storage re-registers itself on its next pop.
            dead = {s for s in self.failed if s in self.dd.storages}
            targets = now_excluded | dead
            unregistered &= targets
            # Unregister a tag only AFTER the team tracker finished draining
            # the server out of the shard map (ref: removeStorageServer at
            # exclusion completion, not observation — unregistering a
            # still-serving member would let the logs trim entries it has
            # not applied).  Convergent: retried every round until every
            # tlog acked, so an unreachable tlog can't permanently pin its
            # discard floor on the excluded server's persisted pop floor.
            pending = sorted(targets - unregistered)
            if pending:
                try:
                    shard_map = await self.dd.read_shard_map()
                except (FdbError, TimeoutError):
                    await self.loop.delay(self.tracker_interval)
                    continue
                still_member = set()
                for _b, _e, team, dest in shard_map:
                    still_member |= set(team) | set(dest)
                for sid in pending:
                    if sid in still_member:
                        continue  # drain in progress
                    ok = True
                    for tl in self.tlogs:
                        try:
                            await tl.pop.get_reply(
                                self.process,
                                TLogPopRequest(tag=sid, unregister=True),
                            )
                        except FdbError:
                            ok = False
                    if ok:
                        unregistered.add(sid)
            await self.loop.delay(self.tracker_interval)

    # --- the relocation queue (ref: DataDistributionQueue.actor.cpp) ---
    def _enqueue(self, item: RelocateShard):
        cur = self._queue.get(item.begin)
        if cur is not None and cur.priority >= item.priority:
            return
        self._queue[item.begin] = item
        self._queue_wake.trigger()

    async def _queue_worker(self):
        while True:
            item = self._pop_best()
            if item is None:
                await self._queue_wake.on_change()
                continue
            if not self.active():
                # Superseded singleton: drain without executing.
                await self.loop.delay(self.tracker_interval)
                continue
            self._inflight.add(item.begin)
            try:
                await self.dd.move(item.begin, item.dest_team)
                self.moves_done += 1
                if item.priority >= PRIORITY_EXCLUSION:
                    self.heals_done += 1
                TraceEvent("DDMoveDone").detail("begin", item.begin).detail(
                    "team", item.dest_team
                ).detail("reason", item.reason).log()
            except ActorCancelled:
                raise
            except (FdbError, TimeoutError, ValueError, RuntimeError) as e:
                # Drop it: the tracker re-derives still-needed moves from
                # the authoritative map (convergence, not bookkeeping).
                TraceEvent("DDMoveFailed", severity=30).detail(
                    "begin", item.begin
                ).detail("error", repr(e)).log()
                await self.loop.delay(self.tracker_interval)
            finally:
                self._inflight.discard(item.begin)

    def _pop_best(self) -> Optional[RelocateShard]:
        best = None
        for b, item in self._queue.items():
            if b in self._inflight:
                continue
            if best is None or item.priority > best.priority:
                best = item
        if best is not None:
            del self._queue[best.begin]
        return best
