"""Role interface structs: the request/reply schema between roles.

Ref: fdbclient/MasterProxyInterface.h (CommitTransactionRequest :76,
GetReadVersionRequest :122), fdbserver/ResolverInterface.h
(ResolveTransactionBatchRequest :83), fdbserver/TLogInterface.h,
fdbclient/StorageServerInterface.h.  Each *Interface dataclass carries the
client-side RequestStreamRefs, like the reference's interface structs carry
RequestStream<T> members.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..client.types import Mutation
from ..conflict.types import TransactionConflictInfo
from ..rpc.stream import RequestStreamRef


# --- sequencer (master's version allocator; ref masterserver.actor.cpp:783) ---


@dataclass
class GetCommitVersionRequest:
    requesting_proxy: str = ""


@dataclass
class GetCommitVersionReply:
    version: int = 0
    prev_version: int = 0


@dataclass
class SequencerInterface:
    get_commit_version: RequestStreamRef = None
    report_committed: RequestStreamRef = None  # proxy -> master committed ver
    get_committed_version: RequestStreamRef = None


# --- proxy (ref fdbclient/MasterProxyInterface.h) ---


@dataclass
class CommitTransactionRequest:
    transaction: "object" = None  # client.types.CommitTransactionRef
    flags: int = 0
    # Sampled-transaction id for the CommitDebug latency chain (ref:
    # debugTransaction / g_traceBatch, NativeAPI.actor.cpp:2376).
    debug_id: Optional[str] = None


# GRV priority flags (ref: GetReadVersionRequest::FLAG_PRIORITY_* —
# batch-priority requests ride a tighter ratekeeper lane).
GRV_FLAG_PRIORITY_BATCH = 1
# Lock-awareness (ref: the LOCK_AWARE transaction option + databaseLockedKey
# checks in commitBatch / getLiveCommittedVersion).
GRV_FLAG_LOCK_AWARE = 2
COMMIT_FLAG_LOCK_AWARE = 1


@dataclass
class GetReadVersionRequest:
    transaction_count: int = 1
    flags: int = 0
    debug_id: Optional[str] = None  # TransactionDebug chain (ref :2698)


@dataclass
class GetRateInfoRequest:
    """The proxy's report riding its rate fetch (ref: GetRateInfoRequest
    carrying totalReleasedTransactions so the ratekeeper sees demand, not
    just supply).  `None` requests remain accepted (legacy probes)."""

    proxy_id: str = "proxy0"
    # Read-version requests queued at the proxy when it fetched (the bound
    # the shed policy enforces; surfaced through status qos).
    grv_queue_depth: int = 0
    # The proxy's passive commit-latency p99 sample (virtual seconds) —
    # the recruited-mode fallback when the ratekeeper has no in-memory
    # trace collector to reassemble latency chains from.
    commit_p99: float = 0.0


@dataclass
class GetKeyServersLocationsRequest:
    """Key -> storage-team lookup (ref: GetKeyServersLocationsRequest
    MasterProxyInterface.h:36; served from the proxy's interception of
    keyServers metadata — the txnStateStore analog)."""

    begin: bytes = b""
    end: bytes = b"\xff"
    limit: int = 1000


@dataclass
class GetKeyServersLocationsReply:
    # (range_begin, range_end_or_None, [StorageInterface]); an empty team
    # means the range is unsharded (client falls back to its default).
    results: List[Tuple[bytes, Optional[bytes], list]] = field(
        default_factory=list
    )


@dataclass
class ProxyInterface:
    commit: RequestStreamRef = None
    get_consistent_read_version: RequestStreamRef = None
    get_key_servers_locations: RequestStreamRef = None
    # Recovery-time injection of the shard map recovered from storage
    # ownership meta (the txnStateStore-recovery analog); request payload is
    # ([(begin, end, [ids])], {id: StorageInterface}).
    load_system_map: RequestStreamRef = None


# --- resolver (ref fdbserver/ResolverInterface.h:83-98) ---


@dataclass
class ResolveTransactionBatchRequest:
    prev_version: int = 0
    version: int = 0
    # Version through which this proxy has already RECEIVED resolve replies
    # (lets the resolver GC its per-proxy reply cache; ref
    # ResolverInterface.h lastReceivedVersion, Resolver.actor.cpp:126).
    last_received_version: int = 0
    transactions: List[TransactionConflictInfo] = field(default_factory=list)
    # State transactions: (index-into-transactions, [Mutation]) for txns that
    # touch the \xff system keyspace.  The resolver retains the committed
    # ones so OTHER proxies learn metadata changes in version order (ref:
    # txnStateTransactions ResolverInterface.h:96, retention :170-190).
    state_txns: List[Tuple[int, list]] = field(default_factory=list)
    proxy_id: str = "proxy0"
    epoch: int = 0  # generation guard: stale-epoch requests are rejected
    # Batch-level CommitDebug id (ref: ResolveTransactionBatchRequest
    # debugID, Resolver.actor.cpp:84).
    debug_id: Optional[str] = None


@dataclass
class ResolveTransactionBatchReply:
    committed: List[int] = field(default_factory=list)  # conflict.types codes
    # [(version, [(committed, [Mutation])])] for every state transaction at
    # versions in (proxy's previous batch, this batch) — i.e. other proxies'
    # metadata commits this proxy has not seen (ref: stateMutations
    # ResolverInterface.h:74, filled at Resolver.actor.cpp:183-189).  Each
    # resolver computes `committed` from its own clipped key space; the
    # proxy applies a state txn only if EVERY resolver reports committed
    # (ref: the min-combine at MasterProxyServer.actor.cpp:455).
    state_mutations: List[Tuple[int, list]] = field(default_factory=list)
    # The batch was resolved on the CPU fallback because a device fault or
    # an open circuit degraded the device path (conflict/device_faults.py);
    # the proxy tags its commit latency sample with it.
    degraded: bool = False
    # Per-transaction abort witnesses (ISSUE 17), parallel to `committed`:
    # None for non-CONFLICT txns, else (conflicting_write_version,
    # losing_read_range_index) — the provenance phase 1 computes on device
    # and would otherwise throw away.  The proxy max/min-combines these
    # across resolvers into the structured not_committed cause the client's
    # retry hint reads.  Empty when witness emission is off
    # (FDB_TPU_WITNESS=0); the proxy then falls back to the bare error.
    witnesses: List = field(default_factory=list)


@dataclass
class ResolutionMetricsReply:
    """Load signal for split balancing (ref: ResolutionMetricsRequest
    ResolverInterface.h:108; the master polls these to drive splits)."""

    ops: int = 0  # sampled conflict-range ops since the last poll


@dataclass
class ResolverSignalsReply:
    """Cheap admission-control probe (ISSUE 8) — the resolver-side signals
    the ratekeeper springs on, all O(1) to produce (no conflict-set row
    walks; see ConflictSet.backend_signal): batches in flight or parked on
    the prevVersion chain, the recent-window resolve-latency p99 in virtual
    seconds, and the PR-3 breaker's backend state.  cpu_mirror_tps is the
    wall-clock-measured CPU-fallback throughput (0.0 = no measurement); sim
    ratekeepers ignore it unless ratekeeper_use_measured_cpu_tps."""

    queue_depth: int = 0
    resolve_p99: float = 0.0
    backend_state: str = "ok"  # ok | degraded | probing (worst shard)
    cpu_mirror_tps: float = 0.0
    degraded_batches: int = 0
    # Total confirmed mirror/device divergences this resolver's
    # consistency checker has caught (ISSUE 9).  Informational for
    # status/qos: each divergence already opened the breaker, so
    # backend_state carries the admission-control consequence.
    mirror_divergence: int = 0
    # Shard-granular fault domains (ISSUE 15): a mesh-sharded resolver
    # reports how many of its shards are degraded/probing, so the
    # ratekeeper can contract the lane PROPORTIONALLY (one sick chip out
    # of 8 is ~1/8 of capacity, not a global degraded clamp).  0/0 for
    # single-device resolvers — the pre-ISSUE-15 spring is unchanged.
    shards_total: int = 0
    shards_degraded: int = 0


@dataclass
class ResolutionSplitRequest:
    """Find the key splitting this resolver's sampled load in [begin, end)
    at `fraction` of its mass (ref: ResolutionSplitRequest
    ResolverInterface.h:118-131, served from the iopsSample)."""

    begin: bytes = b""
    end: Optional[bytes] = None
    fraction: float = 0.5


@dataclass
class ResolverInterface:
    resolve: RequestStreamRef = None
    metrics: RequestStreamRef = None
    split: RequestStreamRef = None
    # Ratekeeper signal probe (ResolverSignalsReply) — separate from
    # `metrics` because that stream's ops counter is reset-on-read for the
    # split balancer; two consumers on one reset stream would starve each
    # other.
    signals: RequestStreamRef = None


# --- tlog (ref fdbserver/TLogInterface.h) ---


@dataclass
class TLogCommitRequest:
    """One version's mutations for THIS tlog, grouped by tag (ref:
    TagPartitionedLogSystem push building per-log, per-tag message bundles,
    TagPartitionedLogSystem.actor.cpp:63).  Each mutation carries its
    commit-order seq so consumers subscribing to several tags replay a
    version's mutations in the exact commit order.  Every tlog receives
    every version (possibly with no tags) to keep the prevVersion chain."""

    prev_version: int = 0
    version: int = 0
    # tag -> [(seq, Mutation)]
    tagged: Dict[str, List[Tuple[int, Mutation]]] = field(default_factory=dict)
    epoch: int = 0  # generation guard (ref: epoch locking at recovery)
    # Highest fully-acked version the proxy knows (ref:
    # knownCommittedVersion riding pushes): consumers may apply up to it
    # even when a log replica is unreachable.
    known_committed: int = 0
    debug_id: Optional[str] = None  # CommitDebug chain (TLog stages)


# Broadcast tags: metadata mutations go everywhere (the private-mutation
# analog, ref ApplyMetadataMutation tagging); un-sharded ranges (no
# keyServers entry yet) use the default tag, also on every tlog.
TAG_ALL = "_all"
TAG_DEFAULT = "_default"


@dataclass
class TLogPeekRequest:
    """Peek the union of `tags` (ref tLogPeekMessages :946; a storage
    subscribes to its own tag + the broadcast tags).

    tags=None subscribes to EVERY tag (a log router pulling the full
    stream).  raw_tagged=True returns entries as (version, {tag: [(seq,
    mutation)]}) instead of the merged (version, [mutations]) — the form a
    router needs to re-serve arbitrary tag subsets downstream; it also
    lets merge cursors dedupe across replicas by (tag, seq)."""

    begin_version: int = 0
    # Merge-cursor mode: instead of erroring peek_below_begin, serve from
    # this log's own floor and report it in `served_from` — a FRESH
    # replacement log (begin = recovery version) holds nothing below by
    # construction; surviving replicas cover that range, so a merge over
    # the set must not wedge on the one log that cannot answer (ref: the
    # best-effort member handling in MergedPeekCursor).
    allow_below_begin: bool = False
    tags: Optional[List[str]] = field(
        default_factory=lambda: [TAG_DEFAULT, TAG_ALL]
    )
    limit_versions: int = 1000
    raw_tagged: bool = False


@dataclass
class TLogPeekReply:
    entries: List[Tuple[int, List[Mutation]]] = field(default_factory=list)
    end_version: int = 0  # exclusive: peeked everything below this
    known_committed: int = 0  # fully-acked watermark (see TLogCommitRequest)
    has_more: bool = False
    # With allow_below_begin: the effective begin actually served (> the
    # request's begin_version when this log's floor is above it).
    served_from: int = 0


@dataclass
class TLogPopRequest:
    """Per-consumer durability mark (ref: tLogPop TLogServer.actor.cpp:894
    pops per TAG; the log discards only below the min across tags).  A
    consumer's first pop registers its tag; a storage registers at
    construction so entries it hasn't peeked are never discarded."""

    version: int = 0  # durable-on-this-consumer; tag's mark rises to it
    tag: str = ""  # consumer identity (storage id); "" = the default tag
    # True when a storage is removed from the cluster for good (DD exclude):
    # its tag stops holding the discard floor, so a dead consumer can't
    # freeze log trimming forever.
    unregister: bool = False


@dataclass
class TLogInterface:
    commit: RequestStreamRef = None
    peek: RequestStreamRef = None
    pop: RequestStreamRef = None
    # Durable-watermark probe (ref: confirmEpochLive / the known-committed
    # version exchange).  Storages bound application to the MIN watermark
    # across their tag's logs, so a version durable on only SOME logs (an
    # un-acked orphan that epoch-end recovery will truncate) is never
    # applied by anyone.
    confirm: RequestStreamRef = None
    # Ratekeeper probe (ref: TLogQueuingMetricsRequest) — durable version +
    # in-memory queue depth.
    metrics: RequestStreamRef = None


@dataclass
class TLogMetricsReply:
    durable_version: int = 0
    queue_bytes: int = 0


# --- storage (ref fdbclient/StorageServerInterface.h) ---


@dataclass
class GetValueRequest:
    key: bytes = b""
    version: int = 0


@dataclass
class GetValueReply:
    value: Optional[bytes] = None
    version: int = 0


@dataclass
class GetKeyValuesRequest:
    begin: bytes = b""
    end: bytes = b"\xff"
    version: int = 0
    limit: int = 1 << 30
    reverse: bool = False


@dataclass
class GetKeyValuesReply:
    data: List[Tuple[bytes, bytes]] = field(default_factory=list)
    more: bool = False
    version: int = 0


@dataclass
class WatchValueRequest:
    """Fire when key's value differs from `value` at or after `version`
    (ref: WatchValueRequest StorageServerInterface.h; watchValue_impl
    storageserver.actor.cpp:760)."""

    key: bytes = b""
    value: Optional[bytes] = None
    version: int = 0


@dataclass
class FetchShardRequest:
    """Page of shard data at a FIXED snapshot version, served during a data
    move (ref: fetchKeys' getRange reads at fetchVersion,
    storageserver.actor.cpp fetchKeys).  The destination pages by advancing
    `begin` past the last returned key, all pages at the same version."""

    begin: bytes = b""
    end: bytes = b"\xff"
    version: int = 0


@dataclass
class FetchShardReply:
    data: List[Tuple[bytes, bytes]] = field(default_factory=list)
    version: int = 0
    more: bool = False


@dataclass
class GetShardStateRequest:
    """Ref: GetShardStateRequest StorageServerInterface.h; DD polls the
    destination until the shard is FETCHED before finishing a move."""

    begin: bytes = b""
    end: bytes = b"\xff"


# GetShardStateReply is a plain string:
#   "readable"  - owned and serving reads over the whole range
#   "adding"    - a fetch is still streaming data in
#   "fetched"   - data complete; waiting for the ownership flip
#   "missing"   - not owned, not being added (e.g. lost across a crash)


@dataclass
class GetStorageMetricsRequest:
    """Byte estimate + split point for a range, from the byte sample (ref:
    WaitMetricsRequest / SplitMetricsRequest, StorageServerInterface.h;
    StorageMetrics.actor.h:404).  end=b"" means open-ended."""

    begin: bytes = b""
    end: bytes = b""
    # Ratekeeper probe: skip the O(n) byte-sample scan, return only the
    # version/queue signals (ref: StorageQueuingMetricsRequest being a
    # separate, cheap request in the reference).
    signals_only: bool = False


@dataclass
class GetStorageMetricsReply:
    bytes: int = 0
    split_key: Optional[bytes] = None  # ~half the sampled bytes below it
    # Ratekeeper signals (ref: StorageQueueInfo fields ride the same
    # metrics fetch in the reference's trackStorageServerQueueInfo).
    version: int = 0
    queue_bytes: int = 0


@dataclass
class GetOwnedMetaRequest:
    """Recovery-time ownership dump: replies (storage_id, [(b, e)] owned,
    server_list) once the storage has replayed the log through min_version,
    so the new proxy's routing map reflects every settled handoff (the
    txnStateStore-recovery analog)."""

    min_version: int = 0


@dataclass
class StorageInterface:
    storage_id: str = ""
    get_storage_metrics: RequestStreamRef = None
    get_value: RequestStreamRef = None
    get_key_values: RequestStreamRef = None
    get_version: RequestStreamRef = None
    watch_value: RequestStreamRef = None
    fetch_shard: RequestStreamRef = None
    get_shard_state: RequestStreamRef = None
    get_owned_meta: RequestStreamRef = None
