"""Dynamic simulated cluster: coordinators + workers + elected controller.

The full control-plane topology (ref: SimulatedCluster.actor.cpp
setupSimulatedSystem): coordinator processes run the generation/leader
registers; worker processes register with whichever cluster controller wins
the election; the CC recruits roles onto workers and re-runs the recovery
state machine whenever a role's process dies.  Clients discover proxies via
the CC's ClientDBInfo long-poll, so they follow recoveries automatically.
"""

from __future__ import annotations

from typing import List, Optional

from ..flow.asyncvar import AsyncVar
from ..flow.error import FdbError
from ..flow.eventloop import EventLoop, set_event_loop
from ..fileio import SimFileSystem
from ..rpc.network import SimNetwork
from .cluster_controller import ClientDBInfo, ClusterController
from .coordination import Coordinator, monitor_leader
from .worker import WorkerServer, run_worker_registration


class DynamicCluster:
    def __init__(
        self,
        seed: int = 1,
        n_coordinators: int = 3,
        n_workers: int = 5,
        n_controllers: int = 2,
        conflict_backend: str = "cpu",
        loop: Optional[EventLoop] = None,
        n_tlogs: int = 1,
        n_storages: int = 1,
        n_proxies: int = 1,
        buggify: bool = True,
        storage_engine: str = "memory",
    ):
        self.loop = loop or EventLoop(seed=seed)
        set_event_loop(self.loop)
        from ..flow.buggify import set_buggify_enabled

        set_buggify_enabled(buggify, self.loop.rng)
        self.net = SimNetwork(self.loop)
        self.fs = SimFileSystem(self.net)
        self.conflict_backend = conflict_backend
        self.storage_engine = storage_engine
        self.n_tlogs = n_tlogs
        self.n_storages = n_storages
        self.n_proxies = n_proxies

        self._coord_procs = [
            self.net.process(f"coord{i}") for i in range(n_coordinators)
        ]
        self._cc_procs = [self.net.process(f"cc{i}") for i in range(n_controllers)]
        self._worker_procs = [
            self.net.process(f"worker{i}") for i in range(n_workers)
        ]
        self._n_clients = 0
        self._build_server_side()

    def _build_server_side(self):
        """Construct coordinator/controller/worker role objects on their
        (live) processes.  Runs at first boot and after crash_and_recover;
        well-known stream tokens are name-derived, so refs held by clients
        stay valid across a rebuild on the same addresses."""
        from .coordination import CoordinatorSet

        self.coordinators = [
            Coordinator(p, fs=self.fs) for p in self._coord_procs
        ]
        # The test-visible "cluster file" (used for NEW clients); survives
        # crash_and_recover so late observers skip the forward hop.  Every
        # server PROCESS below gets its OWN CoordinatorSet — as in the
        # reference each process trusts its own connection file and learns
        # of a quorum change only through coordinator forwarding.
        if not hasattr(self, "coord_set"):
            self.coord_set = CoordinatorSet(
                [p.address for p in self._coord_procs],
                [c.interface() for c in self.coordinators],
            )
        # Server processes boot from the ORIGINAL file contents: after a
        # crash_and_recover that followed a quorum move, they must re-find
        # the cluster through the retired coordinators' durable forwards.
        boot_addrs = [p.address for p in self._coord_procs]

        # Controller candidates: whichever wins the election acts.
        self.controllers = [
            ClusterController(
                p,
                CoordinatorSet(boot_addrs),
                conflict_backend=self.conflict_backend,
                storage_engine=self.storage_engine,
                fs=self.fs,
                n_tlogs=self.n_tlogs,
                n_storages=self.n_storages,
                n_proxies=self.n_proxies,
            )
            for p in self._cc_procs
        ]

        self.workers: List[WorkerServer] = []
        for proc in self._worker_procs:
            w = WorkerServer(proc, self.fs)
            self.workers.append(w)
            leader_var = AsyncVar(None)
            proc.spawn_observed(
                monitor_leader(proc, CoordinatorSet(boot_addrs), leader_var),
                "leader_mon",
            )
            proc.spawn(run_worker_registration(w, leader_var), "registration")

    @property
    def coord_ifaces(self):
        """Live coordinator interfaces (back-compat accessor; the
        retargetable truth is `coord_set`)."""
        return self.coord_set.interfaces

    def crash_and_recover(self):
        """Whole-cluster power loss: kill every server process (coordinators
        included), resolve unsynced disk writes per the corruption model,
        reboot, and rebuild everything from disk.  The cluster manifest must
        come back from the coordinators' files alone (ref:
        restartSimulatedSystem SimulatedCluster.actor.cpp:597 +
        Coordination.actor.cpp OnDemandStore persistence).  Clients survive
        and re-discover the new generation via their long-polls."""
        procs = self._coord_procs + self._cc_procs + self._worker_procs
        for p in procs:
            p.kill()
        for p in procs:
            self.fs.crash_machine(p.machine.machine_id)
        for p in procs:
            p.reboot()
        self._build_server_side()

    # --- clients ---
    def database(self, name: str = ""):
        from ..client.transaction import Database

        from .coordination import CoordinatorSet

        self._n_clients += 1
        proc = self.net.process(name or f"client{self._n_clients}")
        info_var = AsyncVar(ClientDBInfo())
        leader_var = AsyncVar(None)
        # Own connection-file view (snapshot of the cluster-level one);
        # coordinator forwards retarget it if the quorum moves later.
        proc.spawn_observed(
            monitor_leader(
                proc, CoordinatorSet(list(self.coord_set.addresses)), leader_var
            ),
            "leader_mon",
        )
        proc.spawn(
            self._monitor_client_info(proc, leader_var, info_var), "info_mon"
        )
        return Database(proc, info_var=info_var)

    async def _monitor_client_info(self, proc, leader_var, info_var):
        """Long-poll the elected CC for ClientDBInfo (ref: monitorProxies)."""
        loop = self.loop
        while True:
            leader = leader_var.get()
            if leader is None:
                await loop.delay(0.2)
                continue
            cc = next(
                (
                    c
                    for c in self.controllers
                    if c.process.address == leader.address
                ),
                None,
            )
            if cc is None:
                await loop.delay(0.2)
                continue
            try:
                from ..flow.eventloop import timeout_after

                # Bounded long-poll: if we guessed the leader wrong (or it
                # changes), re-check rather than park forever.
                info = await timeout_after(
                    loop,
                    cc.client_info_ref().get_reply(
                        proc, info_var.get().generation
                    ),
                    2.0,
                    default=None,
                )
                if info is not None:
                    info_var.set(info)
            except FdbError:
                await loop.delay(0.2)

    # --- drivers ---
    def run_until(self, future, timeout_vt: float = 1000.0):
        return self.loop.run_until(future, timeout_vt=timeout_vt)

    def run_all(self, coros_by_db, timeout_vt: float = 1000.0):
        from ..flow.eventloop import all_of

        tasks = [db.process.spawn(c) for db, c in coros_by_db]
        return self.run_until(all_of(tasks), timeout_vt=timeout_vt)

    def kill_role_process(self, role: str):
        """Kill the worker process currently hosting `role` (as recruited by
        the acting controller).  Unsuffixed stateful names alias the first
        instance ("tlog" -> "tlog0")."""
        cc = self.acting_controller()
        # Empty before the first recruitment finishes; KeyError then (the
        # caller treats it as "role not recruited yet").
        addrs = getattr(cc, "_role_addrs", {})
        addr = addrs.get(role) or addrs[role + "0"]
        proc = self.net.get_process(addr)
        proc.kill()
        return proc

    def acting_controller(self) -> ClusterController:
        for c in self.controllers:
            # A dead CC's is_leader var is frozen at its last value; only a
            # live process can act.
            if c.process.alive and c.is_leader.get():
                return c
        raise RuntimeError("no controller is leader")
