"""LogRouter: pull the primary's mutation stream, re-serve it remotely.

Ref: fdbserver/LogRouter.actor.cpp — pullAsyncData (:172) tails the
primary log system through a peek cursor into an in-memory window, and
the router answers the same peek/pop protocol the TLogs speak, so remote
consumers (remote-DC storage servers, DR agents) read from their local
router instead of crossing the WAN per consumer.  Consumer pops fold into
the router's floor, which it forwards to the primary logs under its own
registered tag — the primary retains exactly what the slowest remote
consumer still needs (spill bounds the memory there).

The rebuild hosts an in-memory TLog object as the router's buffer: the
serving half (peek/pop/confirm, per-tag floors, trimming) is identical by
construction; only the fill path differs (pulled via MergePeekCursor
instead of pushed commits).
"""

from __future__ import annotations

from typing import List, Optional

from ..flow.error import FdbError
from ..rpc.network import SimProcess
from ..rpc.peek_cursor import MergePeekCursor
from .interfaces import TLogInterface, TLogPopRequest
from .tlog import TLog


class LogRouter:
    def __init__(
        self,
        process: SimProcess,
        primary_logs: List,
        router_id: str = "router0",
        begin_version: int = 0,
        tags: Optional[List[str]] = None,  # None = full stream
        poll: float = 0.01,
        buffer_bytes_limit: int = 16 << 20,  # backpressure bound (ref: the
        # router's buffer limit — it stops pulling, the primary spills)
    ):
        self.process = process
        self.primary_logs = list(primary_logs)
        self.router_tag = f"_lr/{router_id}"
        self.poll = poll
        # The buffer/serving half: an in-memory TLog on this process.
        self.log = TLog(process, epoch_begin_version=begin_version)
        self.cursor = MergePeekCursor(
            process, self.primary_logs, tags=tags, begin=begin_version
        )
        self._forwarded_floor = begin_version
        self.pulled = begin_version
        self.buffer_bytes_limit = buffer_bytes_limit
        # Set when the primary permanently cannot serve our begin (its
        # floor passed us): the operator/recovery must re-point or rebuild
        # this router — retrying would spin forever.
        self.broken: Optional[FdbError] = None
        process.spawn_observed(self._main(), "lr_main")
        process.spawn(self._floor_loop(), "lr_floor")

    async def _main(self):
        # Registration must COMPLETE before the first pull: a concurrent
        # storage pop could advance the primary's floor past our begin in
        # the window between them, breaking the router spuriously.
        await self._register()
        await self._pull_loop()

    def interface(self) -> TLogInterface:
        """Remote consumers treat the router exactly as a log."""
        return self.log.interface()

    async def _register(self):
        """Hold the primary retention floor BEFORE pulling (ref: the
        router tag registered with the log system at recruitment)."""
        for tl in self.primary_logs:
            await tl.pop.get_reply(
                self.process,
                TLogPopRequest(
                    version=self.cursor.begin, tag=self.router_tag
                ),
            )

    async def _pull_loop(self):
        from ..flow.trace import TraceEvent

        loop = self.process.network.loop
        while True:
            if self.log._mem_bytes > self.buffer_bytes_limit:
                # Backpressure: a stalled remote consumer must bound the
                # ROUTER's memory too — stop pulling; the primary retains
                # (and spills) behind our registered floor.
                await loop.delay(0.05)
                continue
            try:
                entries, end = await self.cursor.next_batch()
            except FdbError as e:
                if e.name == "peek_below_begin":
                    # Unrecoverable: the primary's floor passed our begin —
                    # this cursor can never serve the gap.  Surface loudly
                    # and stop (ref: cursor invalidation on epoch end).
                    self.broken = e
                    TraceEvent("LogRouterBroken", severity=30).detail(
                        "router", self.router_tag
                    ).detail("begin", self.cursor.begin).log()
                    return
                # A primary log is unreachable (epoch ending / partition):
                # back off; a recovery will re-point or replace us.
                await loop.delay(0.1)
                continue
            for version, bundle in entries:
                # Feed the buffer directly (the pull IS the commit path).
                self.log.append_raw(version, bundle)
            if end > self.pulled:
                self.pulled = end
                self.log.known_committed = max(
                    self.log.known_committed, self.cursor.known_committed
                )
                self.log.durable.set(end)
                self.log._trim()
            else:
                await loop.delay(self.poll)

    async def _floor_loop(self):
        """Forward the slowest remote consumer's floor to the primary
        (ref: the router popping the log system as its consumers pop)."""
        loop = self.process.network.loop
        while True:
            await loop.delay(0.1)
            floors = self.log.popped_tags
            if not floors:
                continue
            floor = min(min(floors.values()), self.log.durable.get())
            if floor <= self._forwarded_floor:
                continue
            try:
                for tl in self.primary_logs:
                    await tl.pop.get_reply(
                        self.process,
                        TLogPopRequest(version=floor, tag=self.router_tag),
                    )
                self._forwarded_floor = floor
            except FdbError:
                continue  # primary unreachable; retried next round
