"""Worker: the generic process agent that hosts roles on request.

Ref: fdbserver/worker.actor.cpp — workerServer :481 registers with the
cluster controller and spawns role actors from Initialize*Requests
(:494-560); a role's state files live on the worker's machine, so the
controller recruits stateful roles back onto the machines that hold their
disks (the rebuild's stand-in for tag-aware recruitment until replication
lands).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef
from .interfaces import ResolverInterface, SequencerInterface, TLogInterface
from .proxy import Proxy
from .resolver import Resolver
from .sequencer import Sequencer
from .storage import StorageServer
from .tlog import TLog


@dataclass
class WorkerInterface:
    address: str = ""
    init_role: RequestStreamRef = None
    ping: RequestStreamRef = None
    role_check: RequestStreamRef = None
    has_tlog_file: bool = False
    has_storage_file: bool = False


@dataclass
class InitSequencer:
    epoch_begin: int = 0
    epoch: int = 0  # generation, for grant fencing


@dataclass
class InitResolver:
    backend: str = "cpu"
    epoch_begin: int = 0
    epoch: int = 0
    n_proxies: int = 1


@dataclass
class InitTLog:
    epoch_begin: int = 0
    recover_from_disk: bool = True
    epoch: int = 0
    # Replacement for a permanently lost replica: wipe any stale file and
    # start a new durable log that only serves >= epoch_begin.
    fresh: bool = False


@dataclass
class LockTLog:
    """Epoch end: stop the current tlog generation, report durable version
    (ref: TLogServer epoch end locking via TagPartitionedLogSystem)."""


@dataclass
class FastForwardTLog:
    """Jump the recovered tlog's durable chain to the new epoch's begin,
    once the recovery version is fixed (it must exceed the log's true
    durable end, which is only known after recovery from disk).

    `truncate_above`: epoch-end cut (ref: the epochEnd lock protocol,
    TagPartitionedLogSystem.actor.cpp).  Commits ack only after ALL logs
    fsync, so min(recovered durables) bounds every acked version; entries
    above it are un-acked orphans present on a strict subset of logs and
    are discarded (durably, via a truncate marker) before the log serves
    the new epoch."""

    version: int = 0
    truncate_above: Optional[int] = None


@dataclass
class InitStorage:
    tlog: object = None  # TLogInterface or List[TLogInterface]
    engine: str = "memory"  # "memory" | "btree" (ref: openKVStore dispatch)


@dataclass
class ProfilerRequest:
    """Runtime CPU-profiler toggle (ref: ProfilerRequest in
    fdbclient/ClientWorkerInterface.h, handled by worker.actor.cpp; the
    CpuProfiler workload drives it)."""

    enabled: bool = True
    interval: float = 0.005


@dataclass
class InitCoordinator:
    """Start a coordination server on this worker (ref: every fdbserver can
    serve coordination when named in the connection string; the quorum
    change recruits new members this way, ManagementAPI.actor.cpp:684)."""

    pass


@dataclass
class RetireRoles:
    """Tear down EPHEMERAL roles of generations older than `epoch`
    (proxy/resolver/sequencer — their state dies with the generation;
    tlogs stay locked-but-serving for recovery peeks, storages keep
    their data).  A stale role on a live worker otherwise keeps parking
    requests forever — e.g. a resolve waiting on a prevVersion hole —
    and its well-known endpoints shadow nothing (ref: the reference's
    role actors dying with the master they registered with, breaking
    outstanding getReplys via NetNotifiedQueue destruction)."""

    epoch: int = 0


@dataclass
class InitProxy:
    sequencer: SequencerInterface = None
    resolvers: List[ResolverInterface] = field(default_factory=list)
    tlogs: List[TLogInterface] = field(default_factory=list)
    epoch_begin: int = 0
    epoch: int = 0
    proxy_id: str = "proxy0"
    n_proxies: int = 1
    ratekeeper: object = None  # RatekeeperInterface


class WorkerServer:
    def __init__(self, process: SimProcess, fs):
        self.process = process
        self.fs = fs
        self.roles: dict = {}
        self.role_tasks: dict = {}  # role name -> actor tasks to cancel on replace
        self._init_stream = RequestStream(process, "worker_init", well_known=True)
        self._ping_stream = RequestStream(process, "worker_ping", well_known=True)
        self._role_check_stream = RequestStream(
            process, "worker_role_check", well_known=True
        )
        process.spawn_observed(self._serve_init(), "worker_init")
        process.spawn_observed(self._serve_ping(), "worker_ping")
        process.spawn_observed(self._serve_role_check(), "worker_role_check")
        if fs is not None and fs.exists(process, "coordination.dq"):
            # A worker that served coordination (post-quorum-change) must
            # resume it AT BOOT, before any controller exists — elections
            # need the registers up first (ref: coordination starting from
            # the command line/cluster file, not CC recruitment).
            from .coordination import Coordinator

            self.roles["coordinator"] = Coordinator(process, fs=fs)

    def _teardown_role(self, name: str):
        """Cancel a role's actors — construction-time AND owned per-request
        tasks — and break its parked/future requests, so nothing keeps
        waiting on a dead generation (ref: role actors dying with their
        registration, breaking outstanding getReplys)."""
        role = self.roles.get(name)
        for t in self.role_tasks.get(name, []):
            if not t.is_ready():
                t.cancel()
        if role is not None:
            for t in list(getattr(role, "_owned", [])):
                if not t.is_ready():
                    t.cancel()
            for v in vars(role).values():
                if isinstance(v, RequestStream):
                    v.close()

    def _replace_role(self, name: str, role, tasks):
        """Install a new generation's role instance, tearing the previous
        instance down so two generations never run side by side (e.g.
        two storage servers double-applying to one engine file).  NOTE:
        the new role has already re-registered the well-known endpoints
        (replace=True at stream construction), so closing the OLD streams
        here breaks only their parked requests, not new traffic."""
        self._teardown_role(name)
        self.roles[name] = role
        self.role_tasks[name] = tasks

    def interface(self) -> WorkerInterface:
        return WorkerInterface(
            address=self.process.address,
            init_role=self._init_stream.ref(),
            ping=self._ping_stream.ref(),
            role_check=self._role_check_stream.ref(),
            has_tlog_file=self.fs.exists(self.process, "tlog.dq"),
            has_storage_file=self.fs.exists(self.process, "storage.dq"),
        )

    async def _serve_ping(self):
        while True:
            _req, reply = await self._ping_stream.pop()
            reply.send("pong")

    async def _serve_role_check(self):
        """Is a role still installed AND healthy?  A rebooted worker
        answers pings but has an empty role table; a role that marked
        itself `broken` (e.g. a proxy whose commit batch died mid-phase,
        leaving a hole in the prevVersion chain that wedges every later
        batch) is equally unusable on a perfectly live process — the
        reference gets the same recovery because its proxy actor DIES on
        a batch error (ref: per-role waitFailureServer; commitBatch
        errors tearing down the proxy)."""
        while True:
            role_name, reply = await self._role_check_stream.pop()
            role = self.roles.get(role_name)
            reply.send(role is not None and not getattr(role, "broken", False))

    async def _serve_init(self):
        while True:
            req, reply = await self._init_stream.pop()
            self.process.spawn(self._init_one(req, reply), "worker_init_one")

    async def _init_one(self, req, reply):
        from ..flow.buggify import buggify

        if buggify("worker_slow_init"):
            # BUGGIFY: slow recruitment — stretches the recovery window so
            # client retries and stale-generation requests overlap it.
            loop = self.process.network.loop
            await loop.delay(loop.rng.random01() * 0.1)
        # Task capture: actors this process spawns while the role constructs
        # belong to the new role instance (recoveries are driven serially by
        # the CC, so concurrent unrelated spawns are not expected here).
        # Identity-based: spawn() prunes finished tasks, so indices shift.
        before = {id(t) for t in self.process._tasks}

        def new_tasks():
            return [t for t in self.process._tasks if id(t) not in before]

        try:
            if isinstance(req, InitSequencer):
                role = Sequencer(
                    self.process,
                    epoch_begin_version=req.epoch_begin,
                    epoch=req.epoch,
                )
                self._replace_role("sequencer", role, new_tasks())
                reply.send(role.interface())
            elif isinstance(req, InitResolver):
                role = Resolver(
                    self.process,
                    backend=req.backend,
                    epoch_begin_version=req.epoch_begin,
                    epoch=req.epoch,
                    n_proxies=req.n_proxies,
                )
                self._replace_role("resolver", role, new_tasks())
                reply.send(role.interface())
            elif isinstance(req, InitTLog):
                if req.fresh:
                    role = await TLog.fresh(
                        self.process,
                        self.fs,
                        "tlog.dq",
                        epoch_begin=req.epoch_begin,
                        epoch=req.epoch,
                    )
                elif req.recover_from_disk:
                    role = await TLog.recover(
                        self.process,
                        self.fs,
                        "tlog.dq",
                        fast_forward_to=req.epoch_begin,
                        epoch=req.epoch,
                    )
                else:
                    role = TLog(
                        self.process,
                        epoch_begin_version=req.epoch_begin,
                        epoch=req.epoch,
                    )
                self._replace_role("tlog", role, new_tasks())
                reply.send((role.interface(), role.durable.get()))
            elif isinstance(req, RetireRoles):
                retired = []
                for name in ("proxy", "resolver", "sequencer"):
                    role = self.roles.get(name)
                    ep = getattr(role, "epoch", None)
                    if role is None or ep is None or ep >= req.epoch:
                        continue
                    self._teardown_role(name)
                    del self.roles[name]
                    self.role_tasks.pop(name, None)
                    retired.append(name)
                    from ..flow.testprobe import test_probe

                    test_probe("stale_role_retired")
                reply.send(retired)
            elif isinstance(req, LockTLog):
                role: Optional[TLog] = self.roles.get("tlog")
                if role is None:
                    # Distinguishable from a TIMED-OUT lock (None at the
                    # caller): no live role means the disk is quiescent —
                    # safe for recovery to proceed and recover it from
                    # disk; a timeout is NOT safe (the old role may still
                    # be acking commits).
                    reply.send("no_tlog")
                else:
                    role.locked = True
                    reply.send(role.durable.get())
            elif isinstance(req, FastForwardTLog):
                role = self.roles.get("tlog")
                if role is None:
                    reply.send_error("recruitment_failed")
                else:
                    if req.truncate_above is not None:
                        # Epoch-end cut: drop un-acked orphans (durably).
                        await role.truncate_above(req.truncate_above)
                    if req.version > role.durable.get():
                        role.durable.set(req.version)
                    if req.version > role.known_committed:
                        role.known_committed = req.version
                    reply.send(role.durable.get())
            elif isinstance(req, InitStorage):
                role = await StorageServer.recover(
                    self.process,
                    req.tlog,
                    self.fs,
                    "storage.dq" if req.engine == "memory" else "storage.bt",
                    engine=req.engine,
                )
                self._replace_role("storage", role, new_tasks())
                reply.send(role.interface())
            elif isinstance(req, ProfilerRequest):
                from ..flow.profiler import profiler_toggle

                reply.send(profiler_toggle(req.enabled, req.interval))
            elif isinstance(req, InitCoordinator):
                from .coordination import Coordinator

                if "coordinator" not in self.roles:
                    # Idempotent: re-recruiting an existing coordinator must
                    # not reset its registers (its promises are durable).
                    role = Coordinator(self.process, fs=self.fs)
                    self._replace_role("coordinator", role, new_tasks())
                # Joining a quorum un-retires the member: a durable forward
                # from an EARLIER retirement must not shadow the new role.
                await self.roles["coordinator"].clear_forward()
                reply.send("ok")
            elif isinstance(req, InitProxy):
                role = Proxy(
                    self.process,
                    req.sequencer,
                    req.resolvers,
                    req.tlogs,
                    epoch_begin_version=req.epoch_begin,
                    epoch=req.epoch,
                    proxy_id=req.proxy_id,
                    n_proxies=req.n_proxies,
                    ratekeeper=req.ratekeeper,
                )
                self._replace_role("proxy", role, new_tasks())
                reply.send(role.interface())
            else:
                reply.send_error("client_invalid_operation")
        except Exception:  # noqa: BLE001 - recruitment failed; CC retries
            reply.send_error("recruitment_failed")


async def run_worker_registration(
    worker: WorkerServer, cc_leader_var, interval: float = 1.0
):
    """Keep the cluster controller aware of this worker (ref:
    registrationClient worker.actor.cpp; re-registers on CC change)."""
    from ..flow.error import FdbError

    process = worker.process
    loop = process.network.loop
    while True:
        leader = cc_leader_var.get()
        if leader is not None and leader.payload is not None:
            register_ref = leader.payload.get("register_worker")
            if register_ref is not None:
                try:
                    await register_ref.get_reply(process, worker.interface())
                except FdbError:
                    pass
        await loop.delay(interval)
