"""Single-generation simulated cluster: the minimum end-to-end slice.

Wires sequencer + proxy + resolver + tlog + storage on a SimNetwork (ref:
the role wiring worker.actor.cpp does from Initialize*Requests after master
recovery; recovery/recruitment itself arrives with the control plane).
"""

from __future__ import annotations

from typing import Optional

from ..flow.eventloop import EventLoop, set_event_loop
from ..rpc.network import SimNetwork
from .proxy import Proxy
from .resolver import Resolver
from .sequencer import Sequencer
from .storage import StorageServer
from .tlog import TLog


class SimCluster:
    def __init__(
        self,
        seed: int = 1,
        conflict_backend: str = "cpu",
        conflict_set=None,
        loop: Optional[EventLoop] = None,
    ):
        self.loop = loop or EventLoop(seed=seed)
        set_event_loop(self.loop)
        self.net = SimNetwork(self.loop)
        self.master_proc = self.net.process("master")
        self.resolver_proc = self.net.process("resolver")
        self.tlog_proc = self.net.process("tlog")
        self.storage_proc = self.net.process("storage")
        self.proxy_proc = self.net.process("proxy")

        self.sequencer = Sequencer(self.master_proc)
        self.resolver = Resolver(
            self.resolver_proc,
            backend=conflict_backend,
            conflict_set=conflict_set,
        )
        self.tlog = TLog(self.tlog_proc)
        self.storage = StorageServer(self.storage_proc, self.tlog.interface())
        self.proxy = Proxy(
            self.proxy_proc,
            self.sequencer.interface(),
            [self.resolver.interface()],
            [self.tlog.interface()],
        )
        self._n_clients = 0

    def database(self, name: str = ""):
        # Imported here: client.transaction imports server.interfaces (the
        # interface structs live with the client, as in fdbclient/), so a
        # module-level import would be circular.
        from ..client.transaction import Database

        self._n_clients += 1
        proc = self.net.process(name or f"client{self._n_clients}")
        return Database(
            proc, self.proxy.interface(), self.storage.interface()
        )

    def run_until(self, future, timeout_vt: float = 1000.0):
        return self.loop.run_until(future, timeout_vt=timeout_vt)

    def run_all(self, coros_by_db, timeout_vt: float = 1000.0):
        """Spawn one coroutine per (db, coro) pair and run until all done."""
        from ..flow.eventloop import all_of

        tasks = [db.process.spawn(c) for db, c in coros_by_db]
        return self.run_until(all_of(tasks), timeout_vt=timeout_vt)
