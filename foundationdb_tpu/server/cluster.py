"""Single-generation simulated cluster: the minimum end-to-end slice.

Wires sequencer + proxy + resolver + tlog + storage on a SimNetwork (ref:
the role wiring worker.actor.cpp does from Initialize*Requests after master
recovery; recovery/recruitment itself arrives with the control plane).
"""

from __future__ import annotations

from typing import Optional

from ..flow.eventloop import EventLoop, set_event_loop


def even_split_keys(n_resolvers: int) -> list:
    """n-1 single-byte split points partitioning the key space evenly (ref:
    the initial keyResolvers split; dynamic rebalancing via
    ResolutionSplitRequest arrives later)."""
    return [bytes([256 * i // n_resolvers]) for i in range(1, n_resolvers)]
from ..rpc.network import SimNetwork
from .proxy import Proxy
from .resolver import Resolver
from .sequencer import Sequencer
from .storage import StorageServer
from .tlog import TLog


class SimCluster:
    def __init__(
        self,
        seed: int = 1,
        conflict_backend: str = "cpu",
        conflict_set=None,
        loop: Optional[EventLoop] = None,
        durable: bool = False,
        n_resolvers: int = 1,
        n_storages: int = 1,
        n_tlogs: int = 1,
        n_proxies: int = 1,
        buggify: bool = True,
        n_satellite_tlogs: int = 0,  # extra logs carrying EVERY tag,
        # synchronously in the commit ack set (ref: satellite TLogs;
        # the remote region's zero-loss recovery source)
    ):
        self.loop = loop or EventLoop(seed=seed)
        set_event_loop(self.loop)
        # Simulation buggifies by default, like the reference (flow/flow.h
        # :60-67: BUGGIFY only fires under the simulator).
        from ..flow.buggify import set_buggify_enabled

        set_buggify_enabled(buggify, self.loop.rng)
        self.net = SimNetwork(self.loop)
        self.conflict_backend = conflict_backend
        self._conflict_set = conflict_set
        self.durable = durable
        self.fs = None
        self.master_proc = self.net.process("master")
        self.resolver_procs = [
            self.net.process(f"resolver{i}" if i else "resolver")
            for i in range(n_resolvers)
        ]
        self.resolver_proc = self.resolver_procs[0]
        self.n_satellite_tlogs = n_satellite_tlogs
        self.tlog_procs = [
            self.net.process(f"tlog{i}" if i else "tlog")
            for i in range(n_tlogs)
        ] + [
            # Satellites on their own machines (a different DC in spirit;
            # the sim fabric treats machines uniformly).
            self.net.process(f"satlog{i}")
            for i in range(n_satellite_tlogs)
        ]
        self.tlog_proc = self.tlog_procs[0]
        self.storage_procs = [
            self.net.process(f"storage{i}" if i else "storage")
            for i in range(n_storages)
        ]
        self.storage_proc = self.storage_procs[0]
        self.proxy_procs = [
            self.net.process(f"proxy{i}" if i else "proxy")
            for i in range(n_proxies)
        ]
        self.proxy_proc = self.proxy_procs[0]
        self._n_clients = 0
        self.split_keys = even_split_keys(n_resolvers)

        if durable:
            from ..fileio import SimFileSystem

            assert n_resolvers == 1, "durable multi-resolver: use DynamicCluster"
            assert n_storages == 1, "durable multi-storage: use DynamicCluster"
            assert n_tlogs == 1, "durable multi-tlog: use DynamicCluster"
            assert n_satellite_tlogs == 0, "satellites: non-durable SimCluster"
            self.fs = SimFileSystem(self.net)
            self._start_roles_durable(epoch_begin=0)
        else:
            self.sequencer = Sequencer(self.master_proc)
            self.resolvers = [
                Resolver(
                    p,
                    backend=conflict_backend,
                    conflict_set=conflict_set if i == 0 else None,
                    n_proxies=n_proxies,
                )
                for i, p in enumerate(self.resolver_procs)
            ]
            self.resolver = self.resolvers[0]
            self.tlogs = [TLog(p) for p in self.tlog_procs]
            self.tlog = self.tlogs[0]
            tlog_ifaces = [t.interface() for t in self.tlogs]
            # Storage 0 owns everything at bootstrap (including the \xff
            # system keyspace); DD redistributes from there.
            self.storages = [
                StorageServer(
                    p,
                    tlog_ifaces,
                    storage_id=f"ss{i}",
                    owned_all=(i == 0),
                    n_route_logs=n_tlogs,  # satellites excluded from placement
                )
                for i, p in enumerate(self.storage_procs)
            ]
            self.storage = self.storages[0]
            self.proxies = [
                Proxy(
                    p,
                    self.sequencer.interface(),
                    [r.interface() for r in self.resolvers],
                    tlog_ifaces,
                    resolver_split_keys=self.split_keys,
                    proxy_id=f"proxy{i}",
                    n_proxies=n_proxies,
                    n_satellites=n_satellite_tlogs,
                )
                for i, p in enumerate(self.proxy_procs)
            ]
            self.proxy = self.proxies[0]

    def resolver_balancer(self, **kw):
        """A ResolverBalancer polling this cluster's resolvers (its own
        client process; ref: the master-hosted resolution balancing)."""
        from .resolver_balancer import ResolverBalancer

        return ResolverBalancer(
            self.database("balancer"),
            [r.interface() for r in self.resolvers],
            self.split_keys,
            **kw,
        )

    def data_distributor(self):
        """A DataDistributor driving this cluster (its own client process);
        pre-registered with every storage's id -> interface."""
        from .data_distribution import DataDistributor

        dd = DataDistributor(
            self.database("dd"),
            {s.storage_id: s.interface() for s in self.storages},
        )
        return dd

    def dd_role(self, dd=None):
        """A started self-driving DataDistribution role over this cluster
        (ref: the DD singleton control loop, DataDistribution.actor.cpp);
        the DynamicCluster recruits one automatically — here tests opt in."""
        from .dd_role import DataDistributionRole

        return DataDistributionRole(
            dd or self.data_distributor(),
            tlogs=[t.interface() for t in self.tlogs],
        ).start()

    def _start_roles_durable(self, epoch_begin: int):
        """(Re)build all roles from the machines' disks at a new epoch (the
        static stand-in for master recovery's recruitment; the real recovery
        state machine arrives with the control plane)."""

        async def build():
            self.tlog = await TLog.recover(
                self.tlog_proc, self.fs, "tlog.dq", fast_forward_to=epoch_begin
            )
            self.tlogs = [self.tlog]
            self.storage = await StorageServer.recover(
                self.storage_proc, self.tlog.interface(), self.fs, "storage.dq"
            )
            self.storages = [self.storage]
            self.sequencer = Sequencer(
                self.master_proc, epoch_begin_version=epoch_begin
            )
            self.resolver = Resolver(
                self.resolver_proc,
                backend=self.conflict_backend,
                conflict_set=self._conflict_set,
                epoch_begin_version=epoch_begin,
            )
            self.proxy = Proxy(
                self.proxy_proc,
                self.sequencer.interface(),
                [self.resolver.interface()],
                [self.tlog.interface()],
                epoch_begin_version=epoch_begin,
            )
            self.proxies = [self.proxy]

        self.loop.run_until(self.master_proc.spawn(build(), "recovery"))

    def crash_and_recover(self):
        """Kill every server process, resolve unsynced disk writes per the
        corruption model, reboot, and rebuild roles from disk at a new epoch
        (ref: restartSimulatedSystem SimulatedCluster.actor.cpp:597)."""
        assert self.durable, "crash_and_recover requires durable=True"
        from ..flow.knobs import g_knobs

        procs = [
            self.master_proc,
            self.resolver_proc,
            self.tlog_proc,
            self.storage_proc,
            self.proxy_proc,
        ]
        for p in procs:
            p.kill()
        for p in procs:
            self.fs.crash_machine(p.machine.machine_id)
        for p in procs:
            p.reboot()
        # New epoch begins beyond anything the old one may have handed out
        # (ref: recoverFrom picking recoveryTransactionVersion past the old
        # epoch's end, masterserver.actor.cpp:725).
        epoch_begin = (
            self.sequencer.version + g_knobs.server.max_versions_in_flight
        )
        self._start_roles_durable(epoch_begin=epoch_begin)
        # The recovery transaction: an empty commit that advances the chain
        # through the new epoch so storage catches up to GRV-visible versions
        # (ref: the RECOVERY_TRANSACTION state, masterserver.actor.cpp:1158).
        from ..client.types import CommitTransactionRef
        from .interfaces import CommitTransactionRequest

        async def recovery_txn():
            await self.proxy.interface().commit.get_reply(
                self.master_proc,
                CommitTransactionRequest(transaction=CommitTransactionRef()),
            )

        self.loop.run_until(
            self.master_proc.spawn(recovery_txn(), "recovery_txn")
        )

    def database(self, name: str = ""):
        # Imported here: client.transaction imports server.interfaces (the
        # interface structs live with the client, as in fdbclient/), so a
        # module-level import would be circular.
        from ..client.transaction import Database

        self._n_clients += 1
        proc = self.net.process(name or f"client{self._n_clients}")
        return Database(
            proc,
            self.proxy.interface(),
            self.storage.interface(),
            proxies=[p.interface() for p in self.proxies],
        )

    def run_until(self, future, timeout_vt: float = 1000.0):
        return self.loop.run_until(future, timeout_vt=timeout_vt)

    def run_all(self, coros_by_db, timeout_vt: float = 1000.0):
        """Spawn one coroutine per (db, coro) pair and run until all done."""
        from ..flow.eventloop import all_of

        tasks = [db.process.spawn(c) for db, c in coros_by_db]
        return self.run_until(all_of(tasks), timeout_vt=timeout_vt)
