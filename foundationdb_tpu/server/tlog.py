"""TLog role: the durable, tag-partitioned mutation log.

Ref: TLogServer.actor.cpp — commit path appends version -> per-tag message
bundles and fsyncs (TLogQueue/DiskQueue), tLogPeekMessages :946 serves a
tag's stream to storage servers, tLogPop :894 discards below the consumer
floors.  Each entry holds {tag: [(seq, Mutation)]}; a peek returns the
union of the requested tags per version, re-merged into commit order by
seq (a storage subscribes to its own tag plus the broadcast tags).
Per-tag btree spill is still TODO; unspilled data rides the DiskQueue.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Dict, List, Tuple

from ..flow.asyncvar import NotifiedVersion
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from .interfaces import (
    TLogCommitRequest,
    TLogInterface,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
)

# Simulated fsync time for the in-memory log (a DiskQueue with a simulated
# IAsyncFile replaces this in the durability milestone).
COMMIT_DELAY = 0.0005


class TLog:
    def __init__(
        self,
        process: SimProcess,
        epoch_begin_version: int = 0,
        disk_queue=None,
        epoch: int = 0,
        begin_version: int = 0,
    ):
        self.process = process
        self.epoch = epoch
        # First version this log could possibly hold.  A FRESH log recruited
        # to replace a permanently lost replica starts at the recovery
        # version: peeks below it must ERROR (not silently advance past old
        # versions it never saw) so storages fail over to a surviving
        # replica of their tag for old-epoch data (ref: the old-log-system
        # epochs in LogSystemConfig; peek cursors route pre-recovery reads
        # to the previous generation's logs, TagPartitionedLogSystem
        # :568-581).
        self.begin_version = begin_version
        # Parallel sorted lists: versions[i] holds entries[i], a per-tag
        # mutation bundle {tag: [(seq, Mutation)]}.
        self.versions: List[int] = []
        self.entries: List[Dict[str, list]] = []
        self.durable = NotifiedVersion(epoch_begin_version)
        self.known_committed = epoch_begin_version
        self.popped = epoch_begin_version
        # tag -> highest pop seen; entries are discarded below min over tags
        # (ref: per-tag popping, TLogServer.actor.cpp:894).
        self.popped_tags: dict = {}
        self.disk_queue = disk_queue  # None = in-memory (simulated fsync)
        # Epoch-end lock: a locked log rejects further commits (ref: the
        # TLogLockResult protocol during recovery's LOCKING_CSTATE).
        self.locked = False
        self._commit_stream = RequestStream(process, "tlog_commit", well_known=True)
        self._peek_stream = RequestStream(process, "tlog_peek", well_known=True)
        self._pop_stream = RequestStream(process, "tlog_pop", well_known=True)
        self._confirm_stream = RequestStream(
            process, "tlog_confirm", well_known=True
        )
        process.spawn(self._serve_commit(), "tlog_commit")
        process.spawn(self._serve_peek(), "tlog_peek")
        process.spawn(self._serve_pop(), "tlog_pop")
        process.spawn(self._serve_confirm(), "tlog_confirm")

    @classmethod
    async def recover(
        cls,
        process: SimProcess,
        fs,
        filename: str = "tlog.dq",
        fast_forward_to: int = 0,
        epoch: int = 0,
    ) -> "TLog":
        """Reopen the on-disk queue and rebuild the unpopped suffix (ref:
        TLogServer restorePersistentState).  `fast_forward_to` jumps the
        durable chain to the new epoch's begin version so post-recovery
        pushes (whose prevVersion is the recovery version) can land."""
        import pickle

        from ..fileio.diskqueue import DiskQueue

        q, records = await DiskQueue.open(fs, process, filename)
        log = cls(process, disk_queue=q, epoch=epoch)
        for _seq, payload in records:
            rec = pickle.loads(payload)
            if rec[0] == "__truncate__":
                cut = rec[1]
                k = bisect_right(log.versions, cut)
                del log.versions[k:]
                del log.entries[k:]
                continue
            if rec[0] == "__pop__":
                # Restore per-tag consumer floors: without them, the first
                # pop after a recovery would trim entries a slower (or
                # crashed-and-recovering) consumer still needs (ref: the
                # persistTagPoppedKeys range in TLogServer's persistent
                # state, TLogServer.actor.cpp).
                _m, tag, ver, unregister = rec
                if unregister:
                    log.popped_tags.pop(tag, None)
                else:
                    log.popped_tags[tag] = max(
                        log.popped_tags.get(tag, -1), ver
                    )
                continue
            version, tagged = rec
            log.versions.append(version)
            log.entries.append(tagged)
        log.popped = q.popped_seq
        last = log.versions[-1] if log.versions else q.popped_seq
        log.durable.set(max(last, fast_forward_to))
        return log

    def interface(self) -> TLogInterface:
        return TLogInterface(
            commit=self._commit_stream.ref(),
            peek=self._peek_stream.ref(),
            pop=self._pop_stream.ref(),
            confirm=self._confirm_stream.ref(),
        )

    async def _serve_confirm(self):
        while True:
            _req, reply = await self._confirm_stream.pop()
            reply.send(self.durable.get())

    async def truncate_above(self, cut: int):
        """Epoch-end cut: discard versions > cut (never acked — acks need
        every log durable).  Durable via a marker record so a later
        recovery does not resurrect the orphans from the disk queue."""
        k = bisect_right(self.versions, cut)
        if k < len(self.versions):
            del self.versions[k:]
            del self.entries[k:]
        if self.disk_queue is not None:
            import pickle

            # seq = cut+1 so the marker outlives the orphans it erases (the
            # disk queue's recovery drops records with seq <= popped_seq,
            # and consumer floors never exceed the known-committed bound,
            # which is <= cut, until after the new epoch begins).
            self.disk_queue.push(
                cut + 1, pickle.dumps(("__truncate__", cut), protocol=4)
            )
            await self.disk_queue.commit()

    async def _serve_commit(self):
        while True:
            req, reply = await self._commit_stream.pop()
            self.process.spawn(self._commit_one(req, reply), "tlog_commit_one")

    async def _commit_one(self, req: TLogCommitRequest, reply):
        if self.locked or req.epoch != self.epoch:
            # Locked (epoch ended) or a stale generation's proxy reaching a
            # newer log: never silently absorb (ref: epoch locking prevents
            # cross-generation pushes).
            reply.send_error("tlog_stopped")
            return
        from ..flow.buggify import buggify

        if buggify("tlog_slow_fsync"):
            # BUGGIFY: a slow disk — commits ack late, widening the window
            # where a kill strands un-acked data (the epoch-cut path).
            loop = self.process.network.loop
            await loop.delay(loop.rng.random01() * 0.02)
        # Versions are committed in the sequencer's order (ref: TLogServer
        # waits version ordering before appending).
        await self.durable.when_at_least(req.prev_version)
        if self.locked:
            reply.send_error("tlog_stopped")
            return
        if req.version <= self.durable.get():
            reply.send(self.durable.get())  # duplicate
            return
        self.versions.append(req.version)
        self.entries.append(req.tagged)
        if req.known_committed > self.known_committed:
            self.known_committed = req.known_committed
        if self.disk_queue is not None:
            import pickle

            self.disk_queue.push(
                req.version, pickle.dumps((req.version, req.tagged), protocol=4)
            )
            await self.disk_queue.commit()  # real (simulated-file) fsync
        else:
            await self.process.network.loop.delay(COMMIT_DELAY)  # fsync stand-in
        self.durable.set(req.version)
        self._trim()  # consumers with vacuous floors never pop again
        reply.send(req.version)

    @classmethod
    async def fresh(
        cls,
        process: SimProcess,
        fs,
        filename: str = "tlog.dq",
        epoch_begin: int = 0,
        epoch: int = 0,
    ) -> "TLog":
        """A brand-new durable log replacing a permanently lost replica.
        Any stale file from an earlier generation on this machine is
        deleted first — recovering it would resurrect a log that MISSED the
        epochs between its death and now and silently skip mutations."""
        from ..fileio.diskqueue import DiskQueue

        if fs.exists(process, filename):
            fs.delete(process, filename)
        q, _records = await DiskQueue.open(fs, process, filename)
        log = cls(
            process,
            epoch_begin_version=epoch_begin,
            disk_queue=q,
            epoch=epoch,
            begin_version=epoch_begin,
        )
        return log

    async def _serve_peek(self):
        from ..flow.buggify import buggify

        while True:
            req, reply = await self._peek_stream.pop()
            if req.begin_version < self.begin_version or (
                req.begin_version < self.popped
            ):
                # This log cannot answer below its beginning or below its
                # popped floor: silently returning only LATER versions would
                # make the peeker skip data it never saw (loud failure; the
                # consumer rotates to a replica that still has the range).
                reply.send_error("peek_below_begin")
                continue
            # BUGGIFY: tiny peek pages force the has_more continuation path
            # in every consumer (ref: buggified reply size limits).
            limit = 2 if buggify("tlog_peek_truncate") else req.limit_versions
            i = bisect_right(self.versions, req.begin_version)
            j = min(i + limit, len(self.versions))
            # Only durable versions are visible to peeks.
            durable_end = bisect_right(self.versions, self.durable.get())
            j = min(j, durable_end)
            out = []
            for k in range(i, j):
                by_seq: Dict[int, object] = {}
                for tag in req.tags:
                    for seq, m in self.entries[k].get(tag, ()):
                        by_seq[seq] = m  # dedupe: a mutation may ride 2 tags
                if by_seq:
                    out.append(
                        (self.versions[k],
                         [m for _s, m in sorted(by_seq.items())])
                    )
            reply.send(
                TLogPeekReply(
                    entries=out,
                    end_version=self.durable.get()
                    if j == durable_end
                    else self.versions[j - 1] if j > i else req.begin_version,
                    known_committed=self.known_committed,
                    has_more=j < durable_end,
                )
            )

    def _trim(self):
        """Discard below the min consumer floor (ref tLogPop :894).  Capped
        at the durable watermark: vacuous floors (1<<60, from storages that
        never peek this log) must not leak a bogus sequence into the disk
        queue's popped_seq — a recovered log's durable end derives from it."""
        if not self.popped_tags:
            return
        floor = min(min(self.popped_tags.values()), self.durable.get())
        if floor > self.popped:
            self.popped = floor
            k = bisect_right(self.versions, floor)
            del self.versions[:k]
            del self.entries[:k]
            if self.disk_queue is not None:
                # Persisted with the next commit (lazy, like the ref).
                self.disk_queue.pop(floor)

    async def _serve_pop(self):
        import pickle

        while True:
            req, reply = await self._pop_stream.pop()
            tag = req.tag or "_default"
            changed = False
            if req.unregister:
                changed = self.popped_tags.pop(tag, None) is not None
            elif req.version > self.popped_tags.get(tag, -1):
                self.popped_tags[tag] = req.version
                changed = True
            if changed and self.disk_queue is not None:
                # Lazily persisted (rides the next commit).  Losing an
                # unsynced pop record only LOWERS a recovered floor — the
                # log retains more, never less.  seq = durable+1 so the
                # record outlives the pop floor (which never exceeds the
                # tag's own floor <= durable at pop time).
                self.disk_queue.push(
                    self.durable.get() + 1,
                    pickle.dumps(
                        ("__pop__", tag, req.version, req.unregister),
                        protocol=4,
                    ),
                )
            self._trim()
            reply.send(None)
