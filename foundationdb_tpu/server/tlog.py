"""TLog role: the durable, tag-partitioned mutation log.

Ref: TLogServer.actor.cpp — commit path appends version -> per-tag message
bundles and fsyncs (TLogQueue/DiskQueue), tLogPeekMessages :946 serves a
tag's stream to storage servers, tLogPop :894 discards below the consumer
floors.  Each entry holds {tag: [(seq, Mutation)]}; a peek returns the
union of the requested tags per version, re-merged into commit order by
seq (a storage subscribes to its own tag plus the broadcast tags).

Spill (ref: updatePersistentData, TLogServer.actor.cpp:539): when the
in-memory window exceeds `spill_threshold_bytes`, the oldest durable
versions move into a per-tag btree keyspace (`t/<tag>/<version>` in a COW
B+tree file) and the DiskQueue is popped behind them — a lagging or
crashed-but-registered consumer bounds the log's MEMORY, not its
correctness: peeks below the in-memory floor are served from the spill
store.  Consumer pops clear the spilled ranges; the popped floor and the
spill watermark persist in the spill store's meta keys.
"""

from __future__ import annotations

from bisect import bisect_left, bisect_right
from typing import Dict, List, Tuple

from ..flow.asyncvar import NotifiedVersion
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream
from ..rpc.wire import decode_frame, encode_frame
from .interfaces import (
    TLogCommitRequest,
    TLogInterface,
    TLogPeekReply,
    TLogPeekRequest,
    TLogPopRequest,
)

# Simulated fsync time for the in-memory log (a DiskQueue with a simulated
# IAsyncFile replaces this in the durability milestone).
COMMIT_DELAY = 0.0005


class TLog:
    SPILL_META_THROUGH = b"\x00meta/spilled_through"
    SPILL_META_POPPED = b"\x00meta/popped"
    # One marker key per unregistered (dead-consumer) tag.  Durable in
    # the SPILL store, not the disk queue: the __pop__ unregister record
    # is trimmed once the floor passes its seq, and forgetting a dead tag
    # re-opens the unbounded spill leak it exists to stop.
    SPILL_DEAD_TAG_PREFIX = b"\x00meta/dead_tag/"

    def __init__(
        self,
        process: SimProcess,
        epoch_begin_version: int = 0,
        disk_queue=None,
        epoch: int = 0,
        begin_version: int = 0,
        spill_store=None,
        spill_threshold_bytes: int = 1 << 20,
        spill_keep_versions: int = 16,
    ):
        self.process = process
        self.epoch = epoch
        # First version this log could possibly hold.  A FRESH log recruited
        # to replace a permanently lost replica starts at the recovery
        # version: peeks below it must ERROR (not silently advance past old
        # versions it never saw) so storages fail over to a surviving
        # replica of their tag for old-epoch data (ref: the old-log-system
        # epochs in LogSystemConfig; peek cursors route pre-recovery reads
        # to the previous generation's logs, TagPartitionedLogSystem
        # :568-581).
        self.begin_version = begin_version
        # Parallel sorted lists: versions[i] holds entries[i], a per-tag
        # mutation bundle {tag: [(seq, Mutation)]}.
        self.versions: List[int] = []
        self.entries: List[Dict[str, list]] = []
        self.durable = NotifiedVersion(epoch_begin_version)
        self.known_committed = epoch_begin_version
        self.popped = epoch_begin_version
        # tag -> highest pop seen; entries are discarded below min over tags
        # (ref: per-tag popping, TLogServer.actor.cpp:894).
        self.popped_tags: dict = {}
        # Tags unregistered as dead consumers: commits may still tag them
        # until DD heals keyServers, so spill GC must keep collecting their
        # rows (below the global floor) or the spill store grows forever.
        self._dead_tags: set = set()
        self.disk_queue = disk_queue  # None = in-memory (simulated fsync)
        # -- spill state (None spill_store = memory-only log, no spill) --
        self.spill_store = spill_store
        self.spill_threshold_bytes = spill_threshold_bytes
        self.spill_keep_versions = spill_keep_versions
        self.spilled_through = 0  # all versions <= this live in spill_store
        self._spill_gc_floor = 0  # spill rows below this are already deleted
        self._ver_bytes: List[int] = []  # parallel to versions
        self._mem_bytes = 0
        self._spilling = False
        # Epoch-end lock: a locked log rejects further commits (ref: the
        # TLogLockResult protocol during recovery's LOCKING_CSTATE).
        self.locked = False
        self._commit_stream = RequestStream(process, "tlog_commit", well_known=True)
        self._peek_stream = RequestStream(process, "tlog_peek", well_known=True)
        self._pop_stream = RequestStream(process, "tlog_pop", well_known=True)
        self._confirm_stream = RequestStream(
            process, "tlog_confirm", well_known=True
        )
        self._metrics_stream = RequestStream(
            process, "tlog_metrics", well_known=True
        )
        process.spawn_observed(self._serve_commit(), "tlog_commit")
        process.spawn_observed(self._serve_peek(), "tlog_peek")
        process.spawn_observed(self._serve_pop(), "tlog_pop")
        process.spawn_observed(self._serve_confirm(), "tlog_confirm")
        process.spawn_observed(self._serve_metrics(), "tlog_metrics")

    @classmethod
    async def recover(
        cls,
        process: SimProcess,
        fs,
        filename: str = "tlog.dq",
        fast_forward_to: int = 0,
        epoch: int = 0,
    ) -> "TLog":
        """Reopen the on-disk queue and rebuild the unpopped suffix (ref:
        TLogServer restorePersistentState).  `fast_forward_to` jumps the
        durable chain to the new epoch's begin version so post-recovery
        pushes (whose prevVersion is the recovery version) can land."""
        from ..fileio.btree import BTreeKeyValueStore
        from ..fileio.diskqueue import DiskQueue

        q, records = await DiskQueue.open(fs, process, filename)
        spill = await BTreeKeyValueStore.open(fs, process, filename + ".spill")
        log = cls(process, disk_queue=q, epoch=epoch, spill_store=spill)
        raw = spill.read_value(cls.SPILL_META_THROUGH)
        log.spilled_through = int(raw) if raw else 0
        for k, _v in spill.read_range(
            cls.SPILL_DEAD_TAG_PREFIX, cls.SPILL_DEAD_TAG_PREFIX + b"\xff"
        ):
            log._dead_tags.add(
                k[len(cls.SPILL_DEAD_TAG_PREFIX):].decode()
            )
        for _seq, payload in records:
            rec = decode_frame(payload)
            if rec[0] == "__truncate__":
                cut = rec[1]
                k = bisect_right(log.versions, cut)
                log._mem_bytes -= sum(log._ver_bytes[k:])
                del log.versions[k:]
                del log.entries[k:]
                del log._ver_bytes[k:]
                continue
            if rec[0] == "__pop__":
                # Restore per-tag consumer floors: without them, the first
                # pop after a recovery would trim entries a slower (or
                # crashed-and-recovering) consumer still needs (ref: the
                # persistTagPoppedKeys range in TLogServer's persistent
                # state, TLogServer.actor.cpp).
                _m, tag, ver, unregister = rec
                if unregister:
                    log.popped_tags.pop(tag, None)
                    log._dead_tags.add(tag)
                else:
                    log.popped_tags[tag] = max(
                        log.popped_tags.get(tag, -1), ver
                    )
                continue
            version, tagged = rec
            if version <= log.spilled_through:
                continue  # already persisted in the spill store
            log.versions.append(version)
            log.entries.append(tagged)
            log._ver_bytes.append(len(payload))
            log._mem_bytes += len(payload)
        if log.spilled_through > 0:
            # Spilled data survives below the queue's popped pointer; only
            # the spill-store floor marks what consumers really released.
            raw_p = spill.read_value(cls.SPILL_META_POPPED)
            log.popped = int(raw_p) if raw_p else 0
        else:
            log.popped = q.popped_seq
        last = log.versions[-1] if log.versions else max(
            q.popped_seq, log.spilled_through
        )
        log.durable.set(max(last, fast_forward_to))
        return log

    def interface(self) -> TLogInterface:
        return TLogInterface(
            commit=self._commit_stream.ref(),
            peek=self._peek_stream.ref(),
            pop=self._pop_stream.ref(),
            confirm=self._confirm_stream.ref(),
            metrics=self._metrics_stream.ref(),
        )

    async def _serve_confirm(self):
        while True:
            _req, reply = await self._confirm_stream.pop()
            reply.send(self.durable.get())

    async def _serve_metrics(self):
        from .interfaces import TLogMetricsReply

        while True:
            _req, reply = await self._metrics_stream.pop()
            reply.send(
                TLogMetricsReply(
                    durable_version=self.durable.get(),
                    queue_bytes=self._mem_bytes,
                )
            )

    async def truncate_above(self, cut: int):
        """Epoch-end cut: discard versions > cut (never acked — acks need
        every log durable).  Durable via a marker record so a later
        recovery does not resurrect the orphans from the disk queue.
        The SPILL store must be purged too: spilled versions above the cut
        would otherwise be resurrected by _peek_spilled and feed
        rolled-back mutations to the new generation."""
        # Exclude an in-flight spill: it could be parked at its store
        # commit holding versions above the cut; purging before it lands
        # would resurrect them the moment it resumes.  The log is locked at
        # epoch end (and _spill_task bails when locked), so no new spill
        # starts after this wait.
        loop = self.process.network.loop
        while self._spilling:
            await loop.delay(0.001)
        if self.spill_store is not None and self.spilled_through > cut:
            # Scan the whole tag keyspace for rows above the cut (the
            # orphan suffix is small; truncation only happens at epoch
            # end).  Deleting + lowering the watermark is one atomic
            # spill-store commit.
            lo = b"t/"
            while True:
                page = self.spill_store.read_range(lo, b"t0", limit=512)
                for key, _payload in page:
                    if int.from_bytes(key[-8:], "big") > cut:
                        self.spill_store.clear_range(key, key + b"\x00")
                if len(page) < 512:
                    break
                lo = page[-1][0] + b"\x00"
            self.spilled_through = min(self.spilled_through, cut)
            self.spill_store.set(
                self.SPILL_META_THROUGH, b"%d" % self.spilled_through
            )
            await self.spill_store.commit()
        k = bisect_right(self.versions, cut)
        if k < len(self.versions):
            from ..flow.testprobe import test_probe

            test_probe("epoch_orphans_truncated")
            self._mem_bytes -= sum(self._ver_bytes[k:])
            del self.versions[k:]
            del self.entries[k:]
            del self._ver_bytes[k:]
        if self.disk_queue is not None:
            # seq = cut+1 so the marker outlives the orphans it erases (the
            # disk queue's recovery drops records with seq <= popped_seq,
            # and consumer floors never exceed the known-committed bound,
            # which is <= cut, until after the new epoch begins).
            self.disk_queue.push(
                cut + 1, encode_frame(("__truncate__", cut))
            )
            await self.disk_queue.commit()

    async def _serve_commit(self):
        while True:
            req, reply = await self._commit_stream.pop()
            self.process.spawn(self._commit_one(req, reply), "tlog_commit_one")

    async def _commit_one(self, req: TLogCommitRequest, reply):
        if self.locked or req.epoch != self.epoch:
            # Locked (epoch ended) or a stale generation's proxy reaching a
            # newer log: never silently absorb (ref: epoch locking prevents
            # cross-generation pushes).
            reply.send_error("tlog_stopped")
            return
        from ..flow.buggify import buggify

        if buggify("tlog_slow_fsync"):
            # BUGGIFY: a slow disk — commits ack late, widening the window
            # where a kill strands un-acked data (the epoch-cut path).
            loop = self.process.network.loop
            await loop.delay(loop.rng.random01() * 0.02)
        from ..flow.spans import NULL_SPAN, begin_span
        from ..flow.trace import trace_batch

        trace_batch(
            "CommitDebug", "TLog.tLogCommit.BeforeWaitForVersion", req.debug_id
        )
        # Push span (ISSUE 12): prevVersion park + append + fsync for one
        # real push (idle batches carry no payload and record nothing).
        tspan = (
            begin_span(
                "tlog_push", role=f"TLog.{self.process.name}",
                attrs={"version": req.version},
            )
            if req.tagged
            else NULL_SPAN
        )
        # Versions are committed in the sequencer's order (ref: TLogServer
        # waits version ordering before appending).
        await self.durable.when_at_least(req.prev_version)
        if self.locked:
            tspan.end(attrs={"error": "tlog_stopped"})
            reply.send_error("tlog_stopped")
            return
        if req.version <= self.durable.get():
            tspan.end(attrs={"duplicate": 1})
            reply.send(self.durable.get())  # duplicate
            return
        self.versions.append(req.version)
        self.entries.append(req.tagged)
        if req.known_committed > self.known_committed:
            self.known_committed = req.known_committed
        if self.disk_queue is not None:
            payload = encode_frame((req.version, req.tagged))
            self._ver_bytes.append(len(payload))
            self._mem_bytes += len(payload)
            self.disk_queue.push(req.version, payload)
            await self.disk_queue.commit()  # real (simulated-file) fsync
        else:
            size = 64 + sum(
                len(m.param1) + len(m.param2) + 32
                for items in req.tagged.values()
                for _seq, m in items
            )
            self._ver_bytes.append(size)
            self._mem_bytes += size
            await self.process.network.loop.delay(COMMIT_DELAY)  # fsync stand-in
        self.durable.set(req.version)
        tspan.end()
        trace_batch(
            "CommitDebug", "TLog.tLogCommit.AfterTLogCommit", req.debug_id
        )
        self._trim()  # consumers with vacuous floors never pop again
        if (
            self.spill_store is not None
            and not self._spilling
            and self._mem_bytes > self.spill_threshold_bytes
        ):
            self.process.spawn_observed(self._spill_task(), "tlog_spill")
        reply.send(req.version)

    @staticmethod
    def _spill_key(tag: str, version: int) -> bytes:
        return b"t/" + tag.encode() + b"/" + version.to_bytes(8, "big")

    async def _spill_task(self):
        """Move the oldest durable versions into the spill store, then drop
        them from memory and pop the DiskQueue behind them (ref:
        updatePersistentData TLogServer.actor.cpp:539).  One instance runs
        at a time; consumer trims racing the awaits are re-checked by
        version value, never by index."""
        if self._spilling:
            return
        self._spilling = True
        try:
            while (
                not self.locked  # epoch ended: truncate may be purging
                and self._mem_bytes > self.spill_threshold_bytes // 2
                and len(self.versions) > self.spill_keep_versions
            ):
                durable = self.durable.get()
                n = 0
                while (
                    n < len(self.versions) - self.spill_keep_versions
                    and self.versions[n] <= durable
                    and n < 64
                ):
                    n += 1
                if n == 0:
                    return
                cut = self.versions[n - 1]
                for k in range(n):
                    for tag, items in self.entries[k].items():
                        self.spill_store.set(
                            self._spill_key(tag, self.versions[k]),
                            encode_frame(items),
                        )
                from ..flow.testprobe import test_probe

                test_probe("tlog_spilled")
                self.spill_store.set(self.SPILL_META_THROUGH, b"%d" % cut)
                await self.spill_store.commit()
                # Spilled data is durable: drop it from memory (recompute
                # the index — a consumer trim may have raced the commit)
                # and pop the WAL behind it.
                self.spilled_through = max(self.spilled_through, cut)
                k = bisect_right(self.versions, cut)
                self._mem_bytes -= sum(self._ver_bytes[:k])  # fdblint: ignore[RACE002]: trims racing the commit are re-checked by VERSION VALUE — k is re-bisected after the await, never a stale index
                del self.versions[:k]  # fdblint: ignore[RACE002]: same version-value re-check — bisect_right(versions, cut) ran after the await
                del self.entries[:k]  # fdblint: ignore[RACE004]: entries/versions stay index-aligned — every writer trims both under the version-value re-check, and _spilling gates one spill at a time
                del self._ver_bytes[:k]
                if self.disk_queue is not None:
                    self.disk_queue.pop(cut)
                    await self.disk_queue.commit()
        finally:
            self._spilling = False

    def append_raw(self, version: int, tagged: Dict[str, list]):
        """Append a pulled entry directly (the LogRouter's fill path: the
        pull IS the commit).  Keeps the versions/entries/_ver_bytes
        parallel-array invariant and the byte accounting in ONE place."""
        assert not self.versions or version > self.versions[-1]
        size = 64 + sum(
            len(m.param1) + len(m.param2) + 32
            for items in tagged.values()
            for _s, m in items
        )
        self.versions.append(version)
        self.entries.append(tagged)
        self._ver_bytes.append(size)
        self._mem_bytes += size

    @classmethod
    async def fresh(
        cls,
        process: SimProcess,
        fs,
        filename: str = "tlog.dq",
        epoch_begin: int = 0,
        epoch: int = 0,
    ) -> "TLog":
        """A brand-new durable log replacing a permanently lost replica.
        Any stale file from an earlier generation on this machine is
        deleted first — recovering it would resurrect a log that MISSED the
        epochs between its death and now and silently skip mutations."""
        from ..fileio.btree import BTreeKeyValueStore
        from ..fileio.diskqueue import DiskQueue

        for stale in (filename, filename + ".spill"):
            if fs.exists(process, stale):
                fs.delete(process, stale)
        q, _records = await DiskQueue.open(fs, process, filename)
        spill = await BTreeKeyValueStore.open(fs, process, filename + ".spill")
        log = cls(
            process,
            epoch_begin_version=epoch_begin,
            disk_queue=q,
            epoch=epoch,
            begin_version=epoch_begin,
            spill_store=spill,
        )
        return log

    async def _serve_peek(self):
        from ..flow.buggify import buggify

        while True:
            req, reply = await self._peek_stream.pop()
            if req.begin_version < self.begin_version or (
                req.begin_version < self.popped
            ):
                if req.allow_below_begin:
                    # Merge-cursor mode: serve from our floor; the reply's
                    # served_from (= the adjusted begin_version) tells the
                    # merge which range this log did NOT cover, so it can
                    # verify some replica still holds it.
                    req.begin_version = max(self.begin_version, self.popped)
                else:
                    # This log cannot answer below its beginning or below
                    # its popped floor: silently returning only LATER
                    # versions would make the peeker skip data it never
                    # saw (loud failure; the consumer rotates to a replica
                    # that still has the range).
                    reply.send_error("peek_below_begin")
                    continue
            # BUGGIFY: tiny peek pages force the has_more continuation path
            # in every consumer (ref: buggified reply size limits).
            limit = 2 if buggify("tlog_peek_truncate") else req.limit_versions
            if (
                self.spill_store is not None
                and req.begin_version < self.spilled_through
            ):
                reply.send(self._peek_spilled(req, limit))
                continue
            i = bisect_right(self.versions, req.begin_version)
            j = min(i + limit, len(self.versions))
            # Only durable versions are visible to peeks.
            durable_end = bisect_right(self.versions, self.durable.get())
            j = min(j, durable_end)
            out = []
            for k in range(i, j):
                tags = (
                    list(self.entries[k])  # None = subscribe to everything
                    if req.tags is None
                    else req.tags
                )
                if getattr(req, "raw_tagged", False):
                    bundle = {
                        t: list(self.entries[k][t])
                        for t in tags
                        if t in self.entries[k]
                    }
                    if bundle:
                        out.append((self.versions[k], bundle))
                    continue
                by_seq: Dict[int, object] = {}
                for tag in tags:
                    for seq, m in self.entries[k].get(tag, ()):
                        by_seq[seq] = m  # dedupe: a mutation may ride 2 tags
                if by_seq:
                    out.append(
                        (self.versions[k],
                         [m for _s, m in sorted(by_seq.items())])
                    )
            reply.send(
                TLogPeekReply(
                    entries=out,
                    end_version=self.durable.get()
                    if j == durable_end
                    else self.versions[j - 1] if j > i else req.begin_version,
                    known_committed=self.known_committed,
                    has_more=j < durable_end,
                    served_from=req.begin_version,
                )
            )

    def _spill_tag_list(self) -> List[str]:
        """Tags present in the spill store, discovered by prefix hops."""
        tags = []
        lo = b"t/"
        while True:
            page = self.spill_store.read_range(lo, b"t0", limit=1)
            if not page:
                return tags
            key = page[0][0]
            tag = key[2:-9].decode()  # t/<tag>/<8-byte version>
            tags.append(tag)
            # Hop to the first key PAST every "t/<tag>/..." row: "0" is
            # "/"+1, so this also clears tags that EXTEND this one with a
            # "/" segment (e.g. "_lr/r1" after "_lr") — a 0xff-padded hop
            # would sort above those and skip them.
            lo = b"t/" + tag.encode() + b"0"

    def _peek_spilled(self, req: TLogPeekRequest, limit: int) -> TLogPeekReply:
        """Serve a peek whose begin is below the in-memory floor from the
        spill store (ref: the persistentData read path of
        tLogPeekMessages).  Per-tag scans each fetch their first `limit`
        versions; any version inside the merged first `limit` is therefore
        complete across tags."""
        from ..flow.testprobe import test_probe

        test_probe("tlog_peek_spilled")
        req_tags = (
            self._spill_tag_list() if req.tags is None else req.tags
        )
        raw = getattr(req, "raw_tagged", False)
        by_ver_tagged: Dict[int, Dict[str, list]] = {}
        by_ver: Dict[int, Dict[int, object]] = {}
        for tag in req_tags:
            lo = self._spill_key(tag, req.begin_version + 1)
            hi = self._spill_key(tag, self.spilled_through + 1)
            # limit+1: a tag returning exactly `limit` rows must still be
            # detected as possibly-incomplete (truncated ⇒ has_more).
            for k, payload in self.spill_store.read_range(
                lo, hi, limit=limit + 1
            ):
                v = int.from_bytes(k[-8:], "big")
                items = decode_frame(payload)
                if raw:
                    by_ver_tagged.setdefault(v, {})[tag] = items
                d = by_ver.setdefault(v, {})
                for seq, m in items:
                    d[seq] = m
        vers = sorted(by_ver)
        truncated = len(vers) > limit
        vers = vers[:limit]
        if raw:
            out = [(v, by_ver_tagged[v]) for v in vers if by_ver_tagged.get(v)]
        else:
            out = [
                (v, [m for _s, m in sorted(by_ver[v].items())]) for v in vers
            ]
        if truncated:
            end = vers[-1]
            more = True
        else:
            end = self.spilled_through
            more = bool(self.versions)
        return TLogPeekReply(
            entries=out,
            end_version=end,
            known_committed=self.known_committed,
            has_more=more,
            served_from=req.begin_version,
        )

    def _trim(self):
        """Discard below the min consumer floor (ref tLogPop :894).  Capped
        at the durable watermark: vacuous floors (1<<60, from storages that
        never peek this log) must not leak a bogus sequence into the disk
        queue's popped_seq — a recovered log's durable end derives from it."""
        if not self.popped_tags:
            return
        floor = min(min(self.popped_tags.values()), self.durable.get())
        if floor > self.popped:
            self.popped = floor
            k = bisect_right(self.versions, floor)
            self._mem_bytes -= sum(self._ver_bytes[:k])
            del self.versions[:k]
            del self.entries[:k]
            del self._ver_bytes[:k]
            if self.disk_queue is not None:
                # Persisted with the next commit (lazy, like the ref).
                self.disk_queue.pop(floor)
            # Only while spilled rows can still exist below the floor: the
            # no-spill case (and a fully-GC'd spill) must not pay a btree
            # commit per floor advance forever.
            if (
                self.spill_store is not None
                and self.spilled_through > 0
                and self._spill_gc_floor < self.spilled_through
            ):
                self.process.spawn_observed(self._spill_gc(floor), "tlog_spill_gc")

    async def _spill_gc(self, floor: int):
        """Delete spilled data below the global consumer floor and persist
        the floor (one atomic spill-store commit).  Lazily lagging is safe:
        a crash rolls the floor back, the log merely retains more.

        Broadcast tags (TAG_ALL/TAG_DEFAULT) have no registered consumer
        and never appear in popped_tags, yet EVERY commit spills rows for
        them — GC'ing only consumer tags grew the spill store without
        bound.  Below the global floor every consumer is past these rows
        too, so they are collected together.  Likewise UNREGISTERED (dead)
        tags: proxies keep tagging commits for a lost storage until DD
        heals keyServers, and nobody will ever pop those rows."""
        from .interfaces import TAG_ALL, TAG_DEFAULT

        if self._dead_tags:
            from ..flow.testprobe import test_probe

            test_probe("dead_tag_spill_gc")
        for tag in (
            set(self.popped_tags) | self._dead_tags | {TAG_ALL, TAG_DEFAULT}
        ):
            self.spill_store.clear_range(
                self._spill_key(tag, 0), self._spill_key(tag, floor + 1)
            )
        self.spill_store.set(self.SPILL_META_POPPED, b"%d" % floor)
        await self.spill_store.commit()
        self._spill_gc_floor = max(self._spill_gc_floor, floor)

    async def _serve_pop(self):
        while True:
            req, reply = await self._pop_stream.pop()
            tag = req.tag or "_default"
            changed = False
            if req.unregister:
                changed = self.popped_tags.pop(tag, None) is not None
                # Record the death even if this log never saw a pop for the
                # tag — it may still hold (and keep receiving) spilled rows.
                changed = changed or tag not in self._dead_tags
                self._dead_tags.add(tag)
                if changed and self.spill_store is not None:
                    # Durable marker (the __pop__ queue record is trimmed
                    # once the floor passes it); rides the next spill-store
                    # commit — losing an unsynced marker only delays GC one
                    # more unregister/restart cycle, never loses data.
                    self.spill_store.set(
                        self.SPILL_DEAD_TAG_PREFIX + tag.encode(), b"1"
                    )
            elif req.version > self.popped_tags.get(tag, -1):
                self.popped_tags[tag] = req.version
                changed = True
            if changed and self.disk_queue is not None:
                # Lazily persisted (rides the next commit).  Losing an
                # unsynced pop record only LOWERS a recovered floor — the
                # log retains more, never less.  seq = durable+1 so the
                # record outlives the pop floor (which never exceeds the
                # tag's own floor <= durable at pop time).
                self.disk_queue.push(
                    self.durable.get() + 1,
                    encode_frame(
                        ("__pop__", tag, req.version, req.unregister)
                    ),
                )
            self._trim()
            reply.send(None)
