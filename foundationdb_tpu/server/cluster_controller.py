"""Cluster controller: elected singleton that recruits roles and drives
write-subsystem recovery.

Ref: fdbserver/ClusterController.actor.cpp (worker registry + recruitment
:341-659, failure detection :1257, ServerDBInfo broadcast) and the master
recovery state machine (masterserver.actor.cpp :1101-1254: READING_CSTATE ->
LOCKING_CSTATE -> RECRUITING -> RECOVERY_TRANSACTION -> WRITING_CSTATE ->
FULLY_RECOVERED).  For this milestone the CC *hosts* the recovery driver
(the reference recruits a separate master worker; splitting it out is a
later refinement) — the protocol steps and the cstate write-before-serve
ordering follow the reference.

Fault model covered: any single role-process failure (proxy, resolver,
sequencer-host, tlog, storage) triggers a new generation; stateful roles
are recruited back onto workers whose machines hold their disk files.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..flow.asyncvar import AsyncVar
from ..flow.error import ActorCancelled, FdbError
from ..flow.eventloop import timeout_after
from ..flow.knobs import g_knobs
from ..flow.state_sanitizer import audited_dict
from ..flow.trace import TraceEvent
from ..rpc.network import SimProcess
from ..rpc.stream import RequestStream, RequestStreamRef
from ..rpc.wire import decode_frame, encode_frame
from .coordination import (
    CoordinatedState,
    CoordinatorInterface,
    CoordinatorSet,
    LeaderInfo,
    coordinator_interface_at,
    try_become_leader,
)
from .interfaces import CommitTransactionRequest
from .worker import (
    FastForwardTLog,
    InitCoordinator,
    InitProxy,
    InitResolver,
    InitSequencer,
    InitStorage,
    InitTLog,
    LockTLog,
    RetireRoles,
    WorkerInterface,
)

PING_INTERVAL = 0.5
PING_TIMEOUT = 2.0


@dataclass
class ClientDBInfo:
    """What clients need (ref: fdbclient ClientDBInfo: proxy list)."""

    generation: int = 0
    proxy: object = None  # ProxyInterface (first proxy; convenience)
    storage: object = None  # StorageInterface (single-shard v1)
    proxies: list = field(default_factory=list)  # all ProxyInterfaces
    # The acting CC's failure-detector stream (ref: ClientDBInfo carrying
    # what FailureMonitorClient needs).
    failure_monitor: object = None


class ClusterController:
    def __init__(
        self,
        process: SimProcess,
        coordinators: List[CoordinatorInterface],
        conflict_backend: str = "cpu",
        storage_engine: str = "memory",
        n_tlogs: int = 1,
        n_storages: int = 1,
        n_proxies: int = 1,
        fs=None,  # SimFileSystem: the ratekeeper's disk-free spring
    ):
        self.process = process
        self.coordinators = coordinators
        self.fs = fs
        self.conflict_backend = conflict_backend
        self.storage_engine = storage_engine
        self.n_tlogs = n_tlogs
        self.n_storages = n_storages
        self.n_proxies = n_proxies
        # Audited under FDB_TPU_STATE_SANITIZER: written by the register
        # serve loop, the per-worker ping actors and recruitment — the
        # multi-writer shape racecheck RACE004 flags statically.
        self.workers: Dict[str, WorkerInterface] = audited_dict(
            process.network.loop, "cluster_controller.workers"
        )
        # address -> process class (ref: ProcessClass); fed by the config
        # monitor, consulted by the next generation's recruitment.
        self.process_classes: Dict[str, str] = {}
        self.client_info = AsyncVar(ClientDBInfo())
        self._info_waiters: list = []
        self.generation = 0
        self.is_leader = AsyncVar(False)
        self._register_stream = RequestStream(process, "cc_register", well_known=True)
        self._info_stream = RequestStream(process, "cc_client_info", well_known=True)
        self._recovery_needed = AsyncVar(0)  # bumped on role failure
        # Cluster-wide failure detection (ref: failure detection :1257 +
        # the status broadcast): fed by the leader's ping sweep below,
        # consumed by FailureMonitorClient via ClientDBInfo.
        from .failure_monitor import FailureDetector

        self.failure_detector = FailureDetector(process)
        process.spawn_observed(self._failure_ping_sweep(), "cc_failure_sweep")
        change_id = process.network.loop.rng.random_int(1, 1 << 31)
        self._leader_info = LeaderInfo(
            priority=0,
            change_id=change_id,
            address=process.address,
            payload={"register_worker": self._register_stream.ref()},
        )
        process.spawn_observed(
            try_become_leader(
                process, coordinators, self._leader_info, self.is_leader
            ),
            "cc_candidacy",
        )
        process.spawn_observed(self._serve_register(), "cc_register")
        process.spawn_observed(self._serve_client_info(), "cc_info")
        process.spawn(self._run(), "cc_run")

    # --- worker registry (ref RegisterWorkerRequest handling) ---
    async def _serve_register(self):
        while True:
            wi, reply = await self._register_stream.pop()
            fresh = wi.address not in self.workers
            self.workers[wi.address] = wi
            if fresh:
                self._recovery_needed.trigger()  # may unblock recruitment
            reply.send(None)

    async def _serve_client_info(self):
        # Parked long-polls drain on the next client_info change; the list
        # is capped (clients whose waiter was dropped just see a same-
        # generation reply and re-poll) so a stable generation cannot
        # accumulate unbounded waiters.
        while True:
            known_gen, reply = await self._info_stream.pop()
            info = self.client_info.get()
            if info.generation != known_gen and info.proxy is not None:
                reply.send(info)
            elif len(self._info_waiters) < 256:
                self._info_waiters.append(reply)
            else:
                reply.send(info)

    def _publish_client_info(self, info: ClientDBInfo):
        self.client_info.set(info)
        waiters, self._info_waiters = self._info_waiters, []
        for r in waiters:
            r.send(info)

    def client_info_ref(self) -> RequestStreamRef:
        return self._info_stream.ref()

    # --- the CC main loop: hold leadership, run recoveries ---
    async def _run(self):
        loop = self.process.network.loop
        while True:
            if not self.is_leader.get():
                await self.is_leader.on_change()
                continue
            try:
                await self._recovery()
            except ActorCancelled:
                raise
            except Exception as e:  # noqa: BLE001 - any failure: retry
                TraceEvent("RecoveryFailed", severity=20).detail(
                    "error", getattr(e, "name", repr(e))
                ).log()
                await loop.delay(0.5)
                continue
            # Recovered: watch for role failures; any failure -> new recovery.
            await self._watch_roles()

    # --- recovery state machine (ref masterserver :1101-1254) ---
    async def _recovery(self):
        loop = self.process.network.loop

        # Retire the old generation's DD singleton first: its proxies are
        # (or are about to be) dead, and a heal move racing recruitment
        # would thrash against the routing rebuild below.  A still-running
        # startup task (seed commit parked on dead proxies) dies with it.
        for t in list(self.process._tasks):
            if t.name.endswith(("cc_start_dd", "cc_time_keeper")):
                t.cancel()
        if getattr(self, "dd_role", None) is not None:
            self.dd_role.stop()
            self.dd_role = None

        # READING_CSTATE
        cstate = CoordinatedState(self.process, self.coordinators)
        raw = await cstate.read()
        prev = decode_frame(raw) if raw else {"epoch_end": 0}
        # Follow a quorum move: the fenced old state holds only a forward
        # pointer (ref: MovableCoordinatedState reading MovedFrom).  Bounded
        # hops — a chain of moves is one hop per retired quorum.
        for _hop in range(4):
            moved = prev.get("moved_to")
            if not moved:
                break
            TraceEvent("CoordinatorsMovedFollow").detail("to", moved).log()
            # Re-drive the retired members' forwards (best-effort): a CC
            # that crashed between writing the moved_to fence and sending
            # set_forward left the old quorum serving phantom elections —
            # every later recovery that follows the pointer repairs that,
            # so clients/workers on stale cluster files converge.
            if isinstance(self.coordinators, CoordinatorSet):
                for addr, c in zip(
                    self.coordinators.addresses, self.coordinators.interfaces
                ):
                    if addr not in moved:
                        await self._try(
                            c.set_forward.get_reply(self.process, list(moved)),
                            timeout=2.0,
                        )
                self.coordinators.retarget(moved)
            else:
                self.coordinators = [
                    coordinator_interface_at(a) for a in moved
                ]
            cstate = CoordinatedState(self.process, self.coordinators)
            raw = await cstate.read()
            prev = decode_frame(raw) if raw else {"epoch_end": 0}

        # The epoch/generation is monotone ACROSS controller failovers: it is
        # persisted in the manifest and bumped past any previously persisted
        # value (ref: DBCoreState recoveryCount, masterserver recoverFrom).
        # A fresh CC starting at a private counter of 0 must not reuse an
        # epoch a dead controller already recruited with — stale proxies
        # would pass the tlog/resolver epoch checks and drop commits.
        self.generation = max(self.generation, prev.get("generation", 0)) + 1
        if getattr(self, "_wanted_proxies", 0):
            self.n_proxies = self._wanted_proxies
        TraceEvent("RecoveryStarted").detail("generation", self.generation).log()

        # LOCKING_CSTATE: persist the bumped generation BEFORE recruiting so
        # even an aborted recovery permanently retires its epoch (a later
        # recovery — ours or another CC's — reads it and goes higher).
        prev["generation"] = self.generation
        await cstate.set(encode_frame(prev))

        # Wait for a usable worker set: stateful roles MUST return to the
        # machines holding their files (recorded in cstate) — recruiting a
        # fresh empty tlog/storage elsewhere would silently drop
        # acknowledged data.  A shard whose whole storage team is
        # permanently dead means recovery (correctly) waits.
        tlog_ws, storage_ws = await self._wait_workers(
            prev.get("tlog_addrs"), prev.get("storage_addrs")
        )

        # LOCKING: stop every surviving old-generation tlog, learn durable
        # ends (a None slot is a replica declared lost after the grace).
        # A lock that does NOT ack on a live replica FAILS the recovery:
        # proceeding with that log unlocked would let the old generation
        # keep acking commits that the epoch cut below then truncates —
        # acked-data loss (observed risk: CC failover while the old
        # generation is healthy + a transient partition of one lock reply).
        epoch_end = prev["epoch_end"]
        for w in tlog_ws:
            if w is None:
                continue
            lock = await self._try(
                w.init_role.get_reply(self.process, LockTLog())
            )
            if lock is None or isinstance(lock, FdbError):
                raise FdbError("master_tlog_failed")  # _run retries
            # "no_tlog": live worker, no role installed — its disk is
            # quiescent; the later InitTLog(recover_from_disk) owns it.
            if isinstance(lock, int):
                epoch_end = max(epoch_end, lock)

        # RECRUITING (ref worker.actor.cpp :494-560 Initialize* handling).
        # Surviving logs recover first WITHOUT a fast-forward so the true
        # durable ends are known before the recovery version is fixed.
        # Epoch-end cut = min(survivor durables): commits ack only after ALL
        # logs fsync, so anything above the min is an un-acked orphan on a
        # subset of logs and is truncated before the new epoch serves (ref:
        # the epochEnd lock/version agreement,
        # TagPartitionedLogSystem.actor.cpp).  With a lost replica the cut
        # may retain entries whose ack never happened — safe: they were
        # resolved and ordered, their clients saw commit_unknown_result.
        tlog_ifs: list = [None] * len(tlog_ws)
        durables = []
        for i, w in enumerate(tlog_ws):
            if w is None:
                continue
            tlog_if, tlog_durable = await w.init_role.get_reply(
                self.process,
                InitTLog(epoch_begin=0, epoch=self.generation),
            )
            tlog_ifs[i] = tlog_if
            durables.append(tlog_durable)
        cut = min(durables)
        # The cut truncates above it; an acknowledged commit above the cut
        # would be silent data loss — the recorder makes it loud (ref:
        # sim_validation's durability promises, SURVEY §5).
        from ..flow import sim_validation

        sim_validation.expect_at_least(
            loop, "acked_commit", cut, "epoch-end cut below an acked commit"
        )
        epoch_end = max([epoch_end] + durables)
        recovery_version = epoch_end + g_knobs.server.max_versions_in_flight
        for w in tlog_ws:
            if w is not None:
                await w.init_role.get_reply(
                    self.process,
                    FastForwardTLog(
                        version=recovery_version, truncate_above=cut
                    ),
                )
        # Fresh replacements for lost slots, at the SAME ring index so tag
        # placement is stable; they refuse peeks below the recovery version,
        # which routes old-epoch reads to the tag's surviving replicas.
        if any(w is None for w in tlog_ws):
            taken = {w.address for w in tlog_ws if w is not None}
            candidates = [
                self.workers[a]
                for a in sorted(self.workers)
                if a not in taken
            ]
            for i, w in enumerate(tlog_ws):
                if w is not None:
                    continue
                if not candidates:
                    raise FdbError("recruitment_failed")
                repl = candidates.pop(0)
                tlog_ifs[i], _d = await repl.init_role.get_reply(
                    self.process,
                    InitTLog(
                        epoch_begin=recovery_version,
                        epoch=self.generation,
                        fresh=True,
                    ),
                )
                tlog_ws[i] = repl
        stateful_addrs = {w.address for w in tlog_ws} | {
            w.address for w in storage_ws
        }
        seq_w = self._pick_stateless(avoid=stateful_addrs)
        seq_if = await seq_w.init_role.get_reply(
            self.process,
            InitSequencer(epoch_begin=recovery_version, epoch=self.generation),
        )
        # Pick the proxy workers FIRST so the resolver is told the exact
        # proxy count that will be recruited (its state-txn GC waits for
        # every proxy to check in); each worker hosts at most one proxy
        # (role-table key "proxy"), so the count clamps to distinct workers
        # (ref: proxy count vs worker fitness,
        # ClusterController.actor.cpp:527-531).
        proxy_ws = self._pick_distinct_stateless(
            max(1, self.n_proxies), avoid=stateful_addrs
        )
        n_proxies = len(proxy_ws)
        res_w = self._pick_stateless(avoid=stateful_addrs)
        res_if = await res_w.init_role.get_reply(
            self.process,
            InitResolver(
                backend=self.conflict_backend,
                epoch_begin=recovery_version,
                epoch=self.generation,
                n_proxies=n_proxies,
            ),
        )
        # Pre-register every expected storage tag's pop floor on every log
        # BEFORE any storage can apply+pop: otherwise a fast replica's pops
        # trim the log below a slow/re-recruited replica's replay point
        # before that replica's own floor registration lands — a permanent
        # wedge (recovery retries re-init the storage at its old durable
        # version, the log refuses peek_below_begin forever).  Confirmed
        # (get_reply), not fire-and-forget, so the ordering is guaranteed.
        # Retention cost is bounded by the TLog spill.  (Ref: the log
        # system knowing its expected tags from recruitment —
        # TagPartitionedLogSystem's epoch tag sets.)
        from .interfaces import TLogPopRequest

        for w in storage_ws:
            tag = "ss:" + w.address.split(":")[0]
            for tl in tlog_ifs:
                await tl.pop.get_reply(
                    self.process, TLogPopRequest(version=0, tag=tag)
                )
        storage_ifs = []
        for w in storage_ws:
            storage_ifs.append(
                await w.init_role.get_reply(
                    self.process,
                    InitStorage(
                        tlog=list(tlog_ifs), engine=self.storage_engine
                    ),
                )
            )
        from ..flow.eventloop import wait_for_all

        # Ratekeeper singleton: recruited fresh each generation on the CC
        # process, polling the new logs/storages over RPC (ref: the CC's
        # ratekeeper singleton recruitment; trackTLogQueueInfo /
        # trackStorageServerQueueInfo).  The old generation's instance (if
        # any) is retired with its actors.
        from .ratekeeper import Ratekeeper

        for t in list(self.process._tasks):
            if t.name.endswith("rk_update") or t.name.endswith("rk_serve"):
                t.cancel()
        self.ratekeeper = Ratekeeper(
            self.process,
            tlog_ifaces=list(tlog_ifs),
            storage_ifaces=list(storage_ifs),
            fs=self.fs,  # enables the disk-free spring in recruited mode
            # Resolver-path springs (ISSUE 8): queue depth, resolve p99,
            # and the device backend_state over the cheap `signals` probe.
            resolver_ifaces=[res_if],
        )
        rk_if = self.ratekeeper.interface()

        proxy_ifs = await wait_for_all(
            [
                proxy_w.init_role.get_reply(
                    self.process,
                    InitProxy(
                        sequencer=seq_if,
                        resolvers=[res_if],
                        tlogs=list(tlog_ifs),
                        epoch_begin=recovery_version,
                        epoch=self.generation,
                        proxy_id=f"proxy{i}",
                        n_proxies=len(proxy_ws),
                        ratekeeper=rk_if,
                    ),
                )
                for i, proxy_w in enumerate(proxy_ws)
            ]
        )
        proxy_if = proxy_ifs[0]
        self._role_addrs = {
            "sequencer": seq_w.address,
            "resolver": res_w.address,
        }
        for i, w in enumerate(proxy_ws):
            self._role_addrs[f"proxy{i}"] = w.address
        for i, w in enumerate(tlog_ws):
            self._role_addrs[f"tlog{i}"] = w.address
        for i, w in enumerate(storage_ws):
            self._role_addrs[f"storage{i}"] = w.address

        # WRITING_CSTATE — before serving clients (write-before-use).  The
        # stateful-role addresses are part of the manifest so the next
        # recovery waits for the right machines.  A fresh session (read +
        # conditional write): if any other recovery read the cstate since our
        # lock write, this raises coordinated_state_conflict and we abort —
        # exactly the fencing the reference gets from MovableCoordinatedState.
        cstate2 = CoordinatedState(self.process, self.coordinators)
        raw2 = await cstate2.read()
        cur = decode_frame(raw2) if raw2 else {}
        if cur.get("generation", 0) > self.generation:
            # Another controller locked a newer epoch while we recruited;
            # writing our manifest now would regress the generation chain.
            raise FdbError("recovery_superseded")
        await cstate2.set(
            encode_frame(
                {
                    "generation": self.generation,
                    "epoch_end": recovery_version,
                    "tlog_addrs": [w.address for w in tlog_ws],
                    "storage_addrs": [w.address for w in storage_ws],
                }
            )
        )

        from ..flow.buggify import buggify

        if buggify("recovery_slow_cstate"):
            # BUGGIFY: a slow WRITING_CSTATE->serving gap — widens the
            # window where another controller could supersede us.
            await loop.delay(loop.rng.random01() * 0.1)

        # RECOVERY_TRANSACTION: advance the chain into the new epoch.
        from ..client.types import CommitTransactionRef

        recovery_txn_version = await proxy_if.commit.get_reply(
            self.process, CommitTransactionRequest(transaction=CommitTransactionRef())
        )

        # Rebuild the proxy's routing map from every storage's ownership
        # meta once each has replayed through the recovery transaction (the
        # txnStateStore-recovery analog; ref recoverFrom masterserver:725).
        # Must finish before clients see the new generation, and before DD
        # resumes metadata writes.
        from .interfaces import GetOwnedMetaRequest

        server_list: dict = {}
        owned_by: dict = {}  # sid -> [(b, e_or_None)]
        live_if_by_sid: dict = {}  # the RECRUITED interfaces, by reported sid
        for storage_if in storage_ifs:
            meta = await timeout_after(
                loop,
                storage_if.get_owned_meta.get_reply(
                    self.process,
                    GetOwnedMetaRequest(min_version=recovery_txn_version),
                ),
                30.0,
            )
            if meta is None:
                raise FdbError("timed_out")
            sid, owned_ranges, sl = meta
            server_list.update(sl)
            server_list.setdefault(sid, storage_if)
            owned_by[sid] = owned_ranges
            live_if_by_sid[sid] = storage_if
        # Teams on ATOMIC segments: each storage coalesces its own ranges,
        # so teammates' boundaries need not line up — cut at every boundary
        # and compute membership per segment.
        cuts = {b""}
        for ranges in owned_by.values():
            for b, e in ranges:
                cuts.add(b)
                if e is not None:
                    cuts.add(e)
        points = sorted(cuts)  # never empty: b"" is always present
        segs = list(zip(points, points[1:]))
        # Open-ended tail; uncovered segments get an empty team and are
        # dropped below.
        segs.append((points[-1], None))

        def covers(ranges, k):
            return any(
                b <= k and (e is None or k < e) for b, e in ranges
            )

        entries = []
        uncovered = []
        for sb, se in segs:
            team = sorted(
                sid for sid, rs in owned_by.items() if covers(rs, sb)
            )
            if team:
                entries.append((sb, se, team))
            elif sb < b"\xff\xff":
                uncovered.append((sb, se))
        if uncovered and prev.get("storage_addrs"):
            # A previously-owned segment with NO surviving replica: the
            # per-machine loss bound in _wait_workers cannot see per-shard
            # team membership, so a loss pattern can slip past it.
            # Proceeding would silently drop the range from the routing
            # map (acked data unreachable); failing keeps recovery waiting
            # for the machines, the correct behavior (ref: recovery
            # waiting on full logs/teams).  A FRESH cluster (no prior
            # storage_addrs) legitimately has no coverage yet.  This
            # includes TOTAL loss (entries empty, e.g. every returning
            # storage lost its data files) — serving an empty map there
            # would present acked data as an empty database.
            TraceEvent("RecoveryUncoveredShards", severity=30).detail(
                "segments", [(b, e) for b, e in uncovered[:8]]
            ).log()
            raise FdbError("master_recovery_failed")

        # Tags of storages NOT in this generation (declared lost after the
        # grace) are unregistered from the logs: a dead consumer's frozen
        # pop floor would wedge _trim's min-floor and retain every later
        # entry on disk forever.  A revived storage re-registers on its
        # next pop; its data gap is DD-heal's business (the same discipline
        # as exclusion-driven unregistration in dd_role).
        for dead_sid in sorted(set(server_list) - set(owned_by)):
            for tlog_if in tlog_ifs:
                if tlog_if is None:
                    continue
                await self._try(
                    tlog_if.pop.get_reply(
                        self.process,
                        TLogPopRequest(tag=dead_sid, unregister=True),
                    ),
                    timeout=2.0,
                )

        # Database lock state must survive the generation change: read
        # `\xff/dbLocked` from a storage owning it and inject it with the
        # map (ref: the txnStateStore carrying databaseLockedKey through
        # recovery).
        from .interfaces import GetKeyValuesRequest
        from .system_keys import DB_LOCKED_KEY

        locked_uid = None
        lock_sid = next(
            (sid for sid, rs in owned_by.items() if covers(rs, DB_LOCKED_KEY)),
            None,
        )
        # sid -> LIVE recruited interface, recorded in the meta loop: no
        # positional alignment between owned_by and storage_ifs is assumed.
        lock_owner = live_if_by_sid.get(lock_sid) if lock_sid else None
        if lock_owner is not None:
            rep = await timeout_after(
                loop,
                lock_owner.get_key_values.get_reply(
                    self.process,
                    GetKeyValuesRequest(
                        begin=DB_LOCKED_KEY,
                        end=DB_LOCKED_KEY + b"\x00",
                        version=recovery_txn_version,
                    ),
                ),
                10.0,
            )
            if rep is None:
                # NEVER come up unlocked on a read failure: dropping the
                # lock across a generation change would unfence a database
                # the operator believes frozen.  Fail the recovery; _run
                # retries it.
                raise FdbError("timed_out")
            if rep.data:
                locked_uid = rep.data[0][1] or None
        await wait_for_all(
            [
                pif.load_system_map.get_reply(
                    self.process, (entries, server_list, locked_uid)
                )
                for pif in proxy_ifs
            ]
        )

        # FULLY_RECOVERED: publish to clients (drains parked long-polls).
        self._publish_client_info(
            ClientDBInfo(
                generation=self.generation,
                proxy=proxy_if,
                storage=storage_ifs[0],
                proxies=list(proxy_ifs),
                failure_monitor=self.failure_detector.ref(),
            )
        )
        # Recruit the DataDistribution singleton for this generation: seed
        # the authoritative `\xff/keyServers` + `\xff/serverList` map from
        # the owned-meta entries when none exists (the master's
        # RECOVERY_TRANSACTION seeding for new databases), then start the
        # live control loop — team healing, split/merge cadence, rebalance
        # queue (ref: DataDistribution.actor.cpp running under the master).
        # Spawned, NOT awaited: the seed transaction commits through the
        # new proxies, and a role dying right here would otherwise wedge
        # recovery itself (retrying a commit no one serves) instead of
        # letting _watch_roles notice and start the next generation.
        self.process.spawn(
            self._start_data_distribution(
                proxy_ifs, storage_ifs, tlog_ifs, entries, server_list
            ),
            "cc_start_dd",
        )
        # Watch `\xff/conf` for topology changes this generation can't
        # satisfy (ref: the CC recruiting a new generation when the
        # configuration's proxy count changes, changeConfig ->
        # checkDataConfiguration).  One monitor per generation; the old
        # one exits when the generation advances.  The stale flag is only
        # ever CONSUMED by _watch_roles — a recovery completing must not
        # clear a change detected while it ran.
        self.process.spawn(
            self._monitor_config(
                proxy_ifs, storage_ifs[0], self.generation, n_proxies
            ),
            "cc_config_monitor",
        )
        # TimeKeeper: wall-clock -> version samples for timestamp-based
        # restore (ref: the timeKeeper actor,
        # ClusterController.actor.cpp:1625).  Cancelled at the next
        # recovery like the DD starter; one writer per generation.
        self.process.spawn(
            self._time_keeper(proxy_ifs, storage_ifs[0], self.generation),
            "cc_time_keeper",
        )
        # Retire STALE ephemeral roles cluster-wide: a worker not chosen
        # this generation may still host the previous proxy/resolver/
        # sequencer, parking requests forever (e.g. a resolve waiting on a
        # prevVersion hole from the failed generation).  Best-effort per
        # worker — an unreachable one gets the same broadcast next
        # recovery, and its stale roles are epoch-fenced meanwhile.
        from ..flow.eventloop import wait_for_all

        await wait_for_all(
            [
                self.process.spawn(
                    self._try(
                        w.init_role.get_reply(
                            self.process, RetireRoles(epoch=self.generation)
                        ),
                        timeout=2.0,
                    )
                )
                for w in list(self.workers.values())
            ]
        )
        TraceEvent("RecoveryComplete").detail("generation", self.generation).detail(
            "recovery_version", recovery_version
        ).log()

    async def _start_data_distribution(
        self, proxy_ifs, storage_ifs, tlog_ifs, entries, server_list
    ):
        """Seed the authoritative shard map when absent, then recruit the
        DD singleton for this generation (ref: dataDistribution running
        under the master, DataDistribution.actor.cpp; seeding ref: the
        RECOVERY_TRANSACTION for new databases, masterserver.actor.cpp:1158)."""
        from ..client.transaction import Database
        from . import system_keys as sk
        from .data_distribution import DataDistributor
        from .dd_role import DataDistributionRole

        db = Database(
            self.process, proxy_ifs[0], storage_ifs[0], proxies=list(proxy_ifs)
        )

        async def seed(tr):
            tr.options["access_system_keys"] = True
            # Lock-aware like every DD metadata txn: recovery of a LOCKED
            # database must still recruit its DataDistribution singleton.
            tr.options["lock_aware"] = True
            rows = await tr.get_range(sk.KEY_SERVERS_PREFIX, sk.KEY_SERVERS_END)
            if rows:
                return
            for sid, iface in server_list.items():
                tr.set(sk.server_list_key(sid), sk.encode_server_entry(iface))
            for sb, se, team in entries:
                tr.set(
                    sk.key_servers_key(sb),
                    sk.encode_key_servers(list(team), [], se),
                )

        try:
            await db.run(seed)
        except ActorCancelled:
            raise
        except Exception as e:  # noqa: BLE001 - next generation retries
            TraceEvent("DDSeedFailed", severity=20).detail(
                "error", repr(e)
            ).log()
            return
        dd = DataDistributor(db, storages=dict(server_list))
        gen = self.generation
        self.dd_role = DataDistributionRole(
            dd,
            tlogs=list(tlog_ifs),
            active_fn=lambda: self.is_leader.get() and self.generation == gen,
        ).start()

    async def _time_keeper(self, proxy_ifs, storage_if, generation: int):
        """Write one (wall-clock second -> read version) sample per
        time_keeper_delay into the timeKeeper map, trimming entries older
        than delay*max_entries; honors the disable key (ref: timeKeeper,
        ClusterController.actor.cpp:1625-1661 + timeKeeperDisableKey).
        Exits when this generation is superseded or leadership is lost —
        the cancel at the next recovery only covers recoveries run by
        THIS controller (same guard discipline as _monitor_config)."""
        from ..client.transaction import Database
        from .system_keys import (
            TIME_KEEPER_DISABLE_KEY,
            time_keeper_key,
        )

        db = Database(
            self.process, proxy_ifs[0], storage_if, proxies=list(proxy_ifs)
        )
        loop = self.process.network.loop
        delay = g_knobs.server.time_keeper_delay
        ttl = delay * g_knobs.server.time_keeper_max_entries
        while self.generation == generation and self.is_leader.get():
            now = loop.now()

            async def sample(tr, now=now):
                tr.options["access_system_keys"] = True
                tr.options["lock_aware"] = True
                if await tr.get(TIME_KEEPER_DISABLE_KEY) is not None:
                    return
                v = await tr.get_read_version()
                tr.set(time_keeper_key(int(now)), b"%d" % v)
                cutoff = int(now - ttl)
                if cutoff > 0:
                    tr.clear_range(
                        time_keeper_key(0), time_keeper_key(cutoff)
                    )

            try:
                await db.run(sample)
            except (FdbError, TimeoutError):
                pass  # next tick retries; a recovery will replace us
            await loop.delay(delay)

    async def _monitor_config(
        self, proxy_ifs, storage_if, generation: int, recruited_proxies: int
    ):
        """Poll the configuration keys; when the desired proxy count
        differs from what this generation actually RECRUITED, flag the
        generation stale so _watch_roles starts a recovery with the new
        count.  Comparing against the recruited count (not self.n_proxies)
        means a change detected mid-recovery re-flags under the next
        generation's monitor instead of being lost."""
        from ..client.transaction import Database

        db = Database(
            self.process,
            proxy_ifs[0],
            storage_if,
            proxies=list(proxy_ifs),
        )
        loop = self.process.network.loop
        while self.generation == generation and self.is_leader.get():
            # Bounded poll: after a failure-recovery these interfaces are
            # dead and get_configuration would retry broken_promise forever
            # — the timeout re-checks the generation guard instead.
            task = self.process.spawn(
                self._get_conf_swallowing(db), "cc_conf_read"
            )
            conf = await timeout_after(loop, task, 5.0, default=None)
            if conf is None:
                task.cancel()
                await loop.delay(0.2)
                continue
            wanted = conf.get("proxies")
            if wanted and wanted != recruited_proxies:
                TraceEvent("ConfigChangeRequiresRecovery").detail(
                    "proxies_now", recruited_proxies
                ).detail("proxies_wanted", wanted).log()
                self._wanted_proxies = wanted
                self._config_stale = True
                return
            # Process classes: recruitment preferences for the NEXT
            # generation (ref: setclass / ProcessClass fitness).
            task = self.process.spawn(
                self._get_classes_swallowing(db), "cc_class_read"
            )
            classes = await timeout_after(loop, task, 5.0, default=None)
            if classes is None:
                task.cancel()  # dead interfaces would retry forever
            else:
                self.process_classes = classes
            # Coordinator quorum change (ref: changeQuorum
            # ManagementAPI.actor.cpp:684, executed by the controller).
            task = self.process.spawn(
                self._get_coords_swallowing(db), "cc_coords_read"
            )
            wanted_coords = await timeout_after(loop, task, 5.0, default=None)
            if wanted_coords is None:
                task.cancel()
            if (
                wanted_coords
                and isinstance(self.coordinators, CoordinatorSet)
                and list(wanted_coords) != self.coordinators.addresses
            ):
                try:
                    await self._change_coordinators(wanted_coords)
                except FdbError as e:
                    if e.name == "no_such_worker":
                        # Unsatisfiable request (address is not a registered
                        # worker): REJECT it — clear the conf key so the
                        # operator sees the request dropped instead of the
                        # controller retrying a doomed change forever.
                        TraceEvent(
                            "ChangeCoordinatorsRejected", severity=20
                        ).detail("requested", list(wanted_coords)).log()
                        await self._clear_coordinator_request(db)
                        continue
                    TraceEvent("ChangeCoordinatorsFailed", severity=20).detail(
                        "error", getattr(e, "name", repr(e))
                    ).log()
                    await loop.delay(1.0)
                    continue
                # The reference forces a full recovery after a quorum
                # change; ours re-derives every coordinator-held invariant
                # under the new set the same way.
                self._config_stale = True
                return
            await loop.delay(0.5)

    async def _get_conf_swallowing(self, db):
        from ..client.management import get_configuration

        try:
            return await get_configuration(db)
        except (FdbError, ActorCancelled):
            return None

    async def _get_coords_swallowing(self, db):
        from ..client.management import get_requested_coordinators

        try:
            return await get_requested_coordinators(db)
        except (FdbError, ActorCancelled):
            return None

    async def _get_classes_swallowing(self, db):
        from ..client.management import get_process_classes

        try:
            return await get_process_classes(db)
        except (FdbError, ActorCancelled):
            return None

    async def _clear_coordinator_request(self, db):
        from ..client.management import conf_key

        async def txn(tr):
            tr.options["access_system_keys"] = True
            tr.clear(conf_key("coordinators"))

        try:
            await db.run(txn)
        except (FdbError, ActorCancelled):
            pass  # next monitor round retries the rejection

    async def _change_coordinators(self, new_addrs):
        """The movable-state quorum swap (ref: changeQuorum
        ManagementAPI.actor.cpp:684 + MovableCoordinatedState):

          1. recruit a coordination server on every NEW address (idempotent
             for members staying on),
          2. copy the manifest into the new quorum's coordinated state,
          3. fence the old quorum with a moved_to record — any stale
             writer's generation is now below the fence write and fails
             with coordinated_state_conflict,
          4. tell old coordinators to forward election clients,
          5. retarget our own cluster-file view.

        Crash safety: a crash between 2 and 3 leaves the OLD quorum
        authoritative (the copy is unreferenced garbage); after 3 every
        reader follows the pointer, so there is no window with two
        writable quorums."""
        assert isinstance(self.coordinators, CoordinatorSet)
        old_addrs = list(self.coordinators.addresses)
        TraceEvent("ChangeCoordinatorsStart").detail("from", old_addrs).detail(
            "to", list(new_addrs)
        ).log()
        for a in new_addrs:
            if a in old_addrs:
                continue  # already serving coordination
            w = self.workers.get(a)
            if w is None:
                raise FdbError("no_such_worker")
            ok = await self._try(
                w.init_role.get_reply(self.process, InitCoordinator())
            )
            # ALL new members must be up before the state moves (the
            # reference's changeQuorum insists the same).
            if ok != "ok":
                raise FdbError("coordinators_changed")
        old_cs = CoordinatedState(self.process, self.coordinators)
        raw = await old_cs.read()
        new_ifaces = [coordinator_interface_at(a) for a in new_addrs]
        # The NEW quorum's state lives under its OWN membership-derived
        # key (quorum_state_key): with overlapping memberships the shared
        # registers hold both quorums' states side by side, so the
        # moved_to fence below cannot clobber the copied manifest.
        from .coordination import quorum_state_key

        new_cs = CoordinatedState(
            self.process, new_ifaces, key=quorum_state_key(list(new_addrs))
        )
        await new_cs.read()
        await new_cs.set(raw or encode_frame({"epoch_end": 0}))
        await old_cs.set(
            encode_frame({"moved_to": list(new_addrs)})
        )
        for addr, c in zip(old_addrs, old_cs.coordinators):
            if addr in new_addrs:
                # A member STAYING in the quorum must keep serving real
                # elections — forwarding it would out-vote the candidates
                # with the forward pseudo-nominee forever (a majority of
                # stayers would wedge every future election).
                continue
            # Best-effort: a dead old coordinator forwards from its durable
            # registry when it reboots; the moved_to fence already protects
            # safety, and _recovery re-drives forwards when following a
            # moved_to pointer (the crash-between-fence-and-forward window).
            await self._try(
                c.set_forward.get_reply(self.process, list(new_addrs)),
                timeout=2.0,
            )
        self.coordinators.retarget(list(new_addrs))
        TraceEvent("ChangeCoordinatorsDone").detail("to", list(new_addrs)).log()

    async def _wait_workers(self, tlog_addrs=None, storage_addrs=None):
        """(tlog_slots, storage_workers).

        With a previous generation's manifest, wait for THOSE addresses (the
        simulator reboots machines at the same address, so the disks come
        back there).  Fresh cluster: spread the stateful roles over live
        workers — tlogs from the front, storages from the back (they may
        share a worker; each worker hosts at most one of each).

        `tlog_slots` is aligned with the manifest's tlog indices; an entry
        of None marks a replica declared LOST: after
        `recovery_missing_machine_grace` a missing machine stops blocking
        recovery when the survivors still cover all acked data — fewer than
        `log_replication_factor` logs lost means every tag retains at least
        one live replica (commits ack only after ALL logs fsync), and any
        surviving storage suffices to serve what it owns (DD heal restores
        team width afterwards).  Losses at or beyond the replication factor
        keep recovery waiting: proceeding could silently lose acked data.
        """
        from ..flow.eventloop import timeout_after

        loop = self.process.network.loop
        last_count, last_change = -1, loop.now()
        wait_begin = loop.now()
        grace = g_knobs.server.recovery_missing_machine_grace
        # Effective replication clamps to the log count (tlogs_for_tag does
        # the same): with a single log, nothing may be declared lost.
        rf = min(
            g_knobs.server.log_replication_factor,
            len(tlog_addrs) if tlog_addrs else self.n_tlogs,
        )
        while True:
            live = await self._live_workers()
            by_addr = {w.address: w for w in live}
            if len(live) != last_count:
                last_count, last_change = len(live), loop.now()
            grace_over = loop.now() - wait_begin >= grace

            def pick(addrs, count, from_back, max_lost=0):
                if addrs:
                    ws = [by_addr.get(a) for a in addrs]
                    lost = sum(1 for w in ws if w is None)
                    if lost == 0:
                        return ws
                    if grace_over and 0 < lost <= max_lost:
                        TraceEvent("RecoveryProceedingDegraded").detail(
                            "lost",
                            [a for a, w in zip(addrs, ws) if w is None],
                        ).log()
                        return ws
                    return None
                if len(live) < count:
                    return None
                # Fresh cluster: wait for the worker set to stabilize before
                # choosing homes for the disks — recruiting onto the single
                # first-registered worker concentrates every stateful role
                # (and its files) on one machine (ref: the CC waiting on
                # RecruitFromConfiguration until enough workers of suitable
                # fitness exist, ClusterController.actor.cpp:341+).
                if (
                    loop.now() - last_change
                    < g_knobs.server.recruitment_stabilize_window
                ):
                    return None
                return (
                    live[-count:] if from_back else live[:count]
                )

            tlog_ws = pick(tlog_addrs, self.n_tlogs, False, max_lost=rf - 1)
            # At most team_size-1 storages may be lost: a whole team gone
            # means some shard has no surviving replica.
            storage_ws = pick(
                storage_addrs,
                self.n_storages,
                True,
                max_lost=min(
                    g_knobs.server.storage_team_size,
                    len(storage_addrs) if storage_addrs else 1,
                )
                - 1,
            )
            if tlog_ws is not None and storage_ws is not None:
                # Lost storages are dropped (their shards live on surviving
                # teammates); lost tlog slots stay as None so a fresh
                # replacement keeps the tag ring's size and indices.
                return tlog_ws, [w for w in storage_ws if w is not None]
            TraceEvent("RecoveryWaitingForWorkers").detail(
                "tlog_addrs", tlog_addrs
            ).detail("storage_addrs", storage_addrs).log()
            # Wake early if a worker registers (or every 0.5s).
            await timeout_after(
                loop, self._recovery_needed.on_change(), 0.5
            )

    async def _failure_ping_sweep(self):
        """Leader-only sweep: ping every registered worker on a short
        cadence and fold the verdicts into the failure detector (ref: the
        CC's workerAvailabilityWatch feeding failure broadcasts).  The
        sweep never unregisters workers — recoveries do that; this is the
        fast-path liveness signal for routing."""
        loop = self.process.network.loop
        while True:
            if not self.is_leader.get():
                await self.is_leader.on_change()
                continue
            for addr in sorted(self.workers):
                wi = self.workers.get(addr)
                if wi is None:
                    continue
                pong = await self._try(
                    wi.ping.get_reply(self.process, None), timeout=0.3
                )
                self.failure_detector.set_state(addr, pong != "pong")
            await loop.delay(0.5)

    async def _live_workers(self) -> List[WorkerInterface]:
        out = []
        for wi in list(self.workers.values()):
            pong = await self._try(
                wi.ping.get_reply(self.process, None), timeout=PING_TIMEOUT
            )
            if pong == "pong":
                out.append(wi)
            elif self.workers.get(wi.address) is wi:
                # Identity re-check after the ping await: a worker that
                # re-registered during the suspension installed a FRESH
                # interface under this address — evicting by key alone
                # would delete the live registration because the old one
                # timed out.
                del self.workers[wi.address]
        # Deterministic order (registration dict order varies with timing).
        out.sort(key=lambda w: w.address)
        return out

    def _class_penalty(self, addr: str) -> int:
        """Recruitment fitness for STATELESS roles (ref: ProcessClass
        machineClassFitness, ClusterController.actor.cpp:622-659):
        stateless-class first, unset next, stateful classes last."""
        cls = self.process_classes.get(addr, "unset")
        if cls == "stateless":
            return 0
        if cls == "unset":
            return 1
        return 2  # storage / transaction / coordinator: keep stateless off

    def _pick_stateless(self, avoid=()) -> WorkerInterface:
        """Spread stateless roles across live workers round-robin-ish,
        preferring workers NOT in `avoid` (the stateful-disk homes) and the
        best process class so losing a stateless role's process doesn't
        also take the only copy of a disk (ref: fitness-based recruitment
        keeping transaction-class processes off storage,
        ClusterController.actor.cpp:622-659)."""
        addrs = sorted(self.workers, key=lambda a: (self._class_penalty(a), a))
        pool = [a for a in addrs if a not in avoid] or addrs
        best = self._class_penalty(pool[0])
        pool = [a for a in pool if self._class_penalty(a) == best]
        self._rr = getattr(self, "_rr", 0) + 1
        return self.workers[pool[self._rr % len(pool)]]

    def _tiered_rotation(self, addrs: List[str], start: int) -> List[str]:
        """Addresses grouped best-fitness-first, rotated WITHIN each tier:
        rotation spreads load but must never promote a worse-class worker
        over a better one."""
        out: List[str] = []
        for tier in sorted({self._class_penalty(a) for a in addrs}):
            t = [a for a in addrs if self._class_penalty(a) == tier]
            r = start % len(t)
            out.extend(t[r:] + t[:r])
        return out

    def _pick_distinct_stateless(self, n: int, avoid=()) -> List[WorkerInterface]:
        """n workers, all distinct (each worker hosts at most one proxy),
        preferring non-`avoid` workers of the best class; falls back only
        when there aren't enough others."""
        addrs = sorted(self.workers)
        self._rr = getattr(self, "_rr", 0) + 1
        start = self._rr
        pool = self._tiered_rotation(
            [a for a in addrs if a not in avoid], start
        ) + self._tiered_rotation([a for a in addrs if a in avoid], start)
        return [self.workers[a] for a in pool[: min(n, len(pool))]]

    async def _watch_roles(self):
        """Ping every recruited role's worker; any failure starts a new
        generation (ref: masterserver waitFailure on each role -> recovery)."""
        loop = self.process.network.loop
        while self.is_leader.get():
            if getattr(self, "_config_stale", False):
                self._config_stale = False
                return  # back to _run -> recovery with the new topology
            # Snapshot: the role table is rebuilt by a concurrent recovery
            # while this watcher parks on role_check below — iterating the
            # live dict across those awaits dies with "changed size during
            # iteration" instead of returning into the new generation.
            for role, addr in list(self._role_addrs.items()):
                wi = self.workers.get(addr)
                if wi is None:
                    TraceEvent("RoleWorkerLost").detail("role", role).log()
                    return
                # role_check (not just ping): a rebooted worker answers pings
                # but no longer hosts the role.  Worker role-table keys have
                # no index suffix (one tlog/storage per worker).
                installed = await self._try(
                    wi.role_check.get_reply(
                        self.process, role.rstrip("0123456789")
                    ),
                    timeout=PING_TIMEOUT,
                )
                if installed is not True:
                    TraceEvent("RoleFailed").detail("role", role).detail(
                        "address", addr
                    ).log()
                    return  # back to _run -> new recovery
            await loop.delay(PING_INTERVAL)

    async def _try(self, fut, timeout: float = 5.0):
        loop = self.process.network.loop

        async def swallow():
            try:
                return await fut
            except FdbError as e:
                return e

        return await timeout_after(
            loop, self.process.spawn(swallow()), timeout, default=None
        )
